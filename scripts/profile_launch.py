"""Decompose the per-launch cost of the TPU decision path.

Round-3 measurement (BENCH_r03.json) put one 16x4096-decision launch at
167 ms p50 on real TPU v5e vs 39 ms on CPU for the identical host path.
This script isolates where that time goes, on whatever backend it runs on:

  1. tunnel ping        — trivial scalar op, dispatch + fetch round trip
  2. h2d transfer       — the per-launch input payload, timed alone
  3. device compute     — gcra_scan with device-resident inputs, block only
  4. d2h fetch          — np.asarray of the [K, 4, B] compact output
  5. end-to-end         — the bench.py run_launch path for comparison

Usage:  python scripts/profile_launch.py [--cpu] [--trace DIR]

With --trace DIR an xprof trace of the steady-state launches is captured
via throttlecrab_tpu.tpu.profiling.trace for TensorBoard/Perfetto.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, warm=3, iters=10):
    for _ in range(warm):
        fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2], ts[-1]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--trace", default=None)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--depth", type=int, default=16)
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import throttlecrab_tpu  # noqa: F401  (enables x64)
    import jax
    import jax.numpy as jnp

    from throttlecrab_tpu.tpu.kernel import gcra_scan
    from throttlecrab_tpu.tpu.table import BucketTable

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", file=sys.stderr)

    B, K = args.batch, args.depth
    CAP = 1 << 21
    rng = np.random.default_rng(3)
    report = {"device": str(dev), "platform": dev.platform, "B": B, "K": K}

    # ---- 1. tunnel ping --------------------------------------------------
    one = jnp.ones((), jnp.int32)
    add = jax.jit(lambda x: x + 1)
    add(one).block_until_ready()
    p50, p99 = timeit(lambda: np.asarray(add(one)))
    report["ping_ms"] = round(p50 * 1e3, 3)
    print(f"1. ping (scalar op + fetch):      {p50 * 1e3:8.2f} ms", file=sys.stderr)

    # dispatch-only (no fetch): how much of ping is the blocking fetch
    p50, _ = timeit(lambda: add(one).block_until_ready())
    report["ping_noblockfetch_ms"] = round(p50 * 1e3, 3)
    print(f"   ping (block, no np.asarray):   {p50 * 1e3:8.2f} ms", file=sys.stderr)

    # ---- input payload ---------------------------------------------------
    slots = rng.integers(0, CAP - 1, (K, B)).astype(np.int32)
    rank = np.zeros((K, B), np.int32)
    is_last = np.ones((K, B), bool)
    emission = np.full((K, B), 20_000_000, np.int64)
    tol = np.full((K, B), 1_000_000_000, np.int64)
    qty = np.ones((K, B), np.int64)
    valid = np.ones((K, B), bool)
    now = np.full(K, 1_753_000_000_000_000_000, np.int64)
    payload = (slots, rank, is_last, emission, tol, qty, valid, now)
    nbytes = sum(a.nbytes for a in payload)
    report["h2d_bytes"] = nbytes

    # ---- 2. h2d transfer -------------------------------------------------
    def h2d():
        arrs = [jax.device_put(a, dev) for a in payload]
        jax.block_until_ready(arrs)
        return arrs

    p50, p99 = timeit(h2d)
    report["h2d_ms"] = round(p50 * 1e3, 3)
    print(
        f"2. h2d transfer ({nbytes / 1e6:.1f} MB, 8 arrays): {p50 * 1e3:8.2f} ms",
        file=sys.stderr,
    )

    # single fused buffer for comparison
    fused = np.concatenate([a.view(np.uint8).ravel() for a in payload])

    def h2d_fused():
        jax.device_put(fused, dev).block_until_ready()

    p50, _ = timeit(h2d_fused)
    report["h2d_fused_ms"] = round(p50 * 1e3, 3)
    print(f"   h2d one fused buffer:          {p50 * 1e3:8.2f} ms", file=sys.stderr)

    # ---- 3. device compute (inputs resident, output blocked not fetched) --
    table = BucketTable(CAP)
    dev_payload = h2d()

    def compute():
        nonlocal table
        table.state, out = gcra_scan(
            table.state, *dev_payload, with_degen=False, compact=True
        )
        out.block_until_ready()
        return out

    p50, p99 = timeit(compute)
    report["compute_ms"] = round(p50 * 1e3, 3)
    report["compute_p99_ms"] = round(p99 * 1e3, 3)
    print(f"3. device compute (scan x{K}):     {p50 * 1e3:8.2f} ms", file=sys.stderr)

    # ---- 4. d2h fetch ----------------------------------------------------
    out = compute()
    report["d2h_bytes"] = out.size * 4

    p50, _ = timeit(lambda: np.asarray(out))
    report["d2h_ms"] = round(p50 * 1e3, 3)
    print(
        f"4. d2h fetch ({out.size * 4 / 1e6:.1f} MB compact out): {p50 * 1e3:8.2f} ms",
        file=sys.stderr,
    )

    # ---- 5. end-to-end: h2d + compute + fetch ----------------------------
    def end_to_end():
        nonlocal table
        arrs = [jax.device_put(a, dev) for a in payload]
        table.state, out = gcra_scan(
            table.state, *arrs, with_degen=False, compact=True
        )
        return np.asarray(out)

    p50, p99 = timeit(end_to_end)
    report["e2e_ms"] = round(p50 * 1e3, 3)
    report["e2e_p99_ms"] = round(p99 * 1e3, 3)
    rate = K * B / p50
    report["e2e_decisions_per_s"] = round(rate)
    print(
        f"5. end-to-end launch:             {p50 * 1e3:8.2f} ms "
        f"({rate / 1e6:.2f} M decisions/s)",
        file=sys.stderr,
    )

    # ---- 5b. pipelined: dispatch N+1 before fetching N's output ----------
    def pipelined(n_launch=8):
        nonlocal table
        pending = None
        t0 = time.perf_counter()
        for _ in range(n_launch):
            arrs = [jax.device_put(a, dev) for a in payload]
            table.state, out = gcra_scan(
                table.state, *arrs, with_degen=False, compact=True
            )
            if pending is not None:
                np.asarray(pending)
            pending = out
        np.asarray(pending)
        return (time.perf_counter() - t0) / n_launch

    pipelined(2)
    per = min(pipelined() for _ in range(3))
    report["pipelined_ms"] = round(per * 1e3, 3)
    report["pipelined_decisions_per_s"] = round(K * B / per)
    print(
        f"5b. pipelined launch:             {per * 1e3:8.2f} ms "
        f"({K * B / per / 1e6:.2f} M decisions/s)",
        file=sys.stderr,
    )

    if args.trace:
        from throttlecrab_tpu.tpu.profiling import trace

        with trace(args.trace):
            for _ in range(4):
                end_to_end()
        print(f"xprof trace written to {args.trace}", file=sys.stderr)
        report["trace_dir"] = args.trace

    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
