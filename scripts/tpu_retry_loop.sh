#!/bin/bash
# Patient TPU-recovery loop for a wedged axon relay.
#
# The relay can stay wedged for hours-to-rounds (docs/
# tpu-launch-profile.md "Operational hazard"); in round 5 every claim
# attempt either hung silently or failed after ~25 min with
# "UNAVAILABLE: TPU backend setup/compile error".  This loop keeps a
# claim attempt in flight (never timeout-killed — killing a mid-claim
# process is what poisons the relay) and, on the FIRST healthy claim,
# immediately captures the round's hardware evidence in priority order:
#
#   1. scripts/probe_sharded_1dev.py  — the round-4 known-issue repro
#      (TESTING.md), highest-value single artifact;
#   2. python bench.py               — the headline number (auto-selects
#      the ids20 + w32 minimum-wire tiers on TPU);
#   3. python bench.py --wire cur    — the A/B that isolates the w32
#      fetch halving.
#
# Run it detached:  nohup scripts/tpu_retry_loop.sh &
# Poll:             tail -f /tmp/tpu_retry.log
cd "$(dirname "$0")/.." || exit 1
LOG=${TPU_RETRY_LOG:-/tmp/tpu_retry.log}
for i in $(seq 1 200); do
  echo "=== attempt $i $(date +%H:%M:%S)" >> "$LOG"
  python scripts/tpu_wait_probe.py >> "$LOG" 2>&1
  rc=$?
  echo "=== attempt $i rc=$rc" >> "$LOG"
  if [ $rc -eq 0 ]; then
    echo "=== TUNNEL HEALTHY, capturing evidence" >> "$LOG"
    python scripts/probe_sharded_1dev.py > /tmp/probe_sharded_tpu.log 2>&1
    echo "=== probe_sharded rc=$?" >> "$LOG"
    python bench.py > /tmp/bench_tpu_r5.log 2>&1
    echo "=== bench rc=$?" >> "$LOG"
    python bench.py --wire cur --no-resident > /tmp/bench_tpu_r5_cur.log 2>&1
    echo "=== bench(cur A/B) rc=$? DONE" >> "$LOG"
    exit 0
  fi
  sleep 150
done
