"""Probe the async-dispatch behavior of the accelerator tunnel.

Answers three questions the launch profiler raised:
  a) does jax.device_put return before the transfer completes?
  b) do back-to-back launch dispatches queue asynchronously (N launches,
     one block == latency + N * device_time) or serialize (N * latency)?
  c) how does scan depth K scale device time?
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import throttlecrab_tpu  # noqa: F401
import jax

if "--cpu" in sys.argv:
    # Env var alone is not enough: the accelerator plugin in
    # sitecustomize re-points JAX after the environment is read.
    jax.config.update("jax_platforms", "cpu")
from throttlecrab_tpu.tpu.kernel import gcra_scan
from throttlecrab_tpu.tpu.table import BucketTable

dev = jax.devices()[0]
print(f"device: {dev}", file=sys.stderr)

B, CAP = 4096, 1 << 21
rng = np.random.default_rng(3)


def payload(K):
    return (
        rng.integers(0, CAP - 1, (K, B)).astype(np.int32),
        np.zeros((K, B), np.int32),
        np.ones((K, B), bool),
        np.full((K, B), 20_000_000, np.int64),
        np.full((K, B), 1_000_000_000, np.int64),
        np.ones((K, B), np.int64),
        np.ones((K, B), bool),
        np.full(K, 1_753_000_000_000_000_000, np.int64),
    )


# ---- a) device_put async? -----------------------------------------------
big = np.ones(2_000_000, np.int32)
jax.device_put(big, dev).block_until_ready()
t0 = time.perf_counter()
x = jax.device_put(big, dev)
t_ret = time.perf_counter() - t0
x.block_until_ready()
t_done = time.perf_counter() - t0
print(f"a) device_put 8MB: returns in {t_ret*1e3:.2f} ms, done in {t_done*1e3:.2f} ms")

# ---- b) async dispatch depth --------------------------------------------
table = BucketTable(CAP)
pay = payload(16)
dev_pay = [jax.device_put(a, dev) for a in pay]
jax.block_until_ready(dev_pay)

# warm compile
table.state, out = gcra_scan(table.state, *dev_pay, with_degen=False, compact=True)
out.block_until_ready()

for n in (1, 2, 4, 8):
    t0 = time.perf_counter()
    outs = []
    for _ in range(n):
        table.state, out = gcra_scan(
            table.state, *dev_pay, with_degen=False, compact=True
        )
        outs.append(out)
    t_disp = time.perf_counter() - t0
    np.asarray(outs[-1])
    t_all = time.perf_counter() - t0
    print(
        f"b) {n} launches (device-resident inputs): dispatch {t_disp*1e3:7.2f} ms, "
        f"total {t_all*1e3:7.2f} ms  ({t_all/n*1e3:6.2f} ms/launch)"
    )

# same but with fresh host->device transfer per launch (the serving shape)
for n in (1, 4, 8):
    t0 = time.perf_counter()
    outs = []
    for _ in range(n):
        arrs = [jax.device_put(a, dev) for a in pay]
        table.state, out = gcra_scan(
            table.state, *arrs, with_degen=False, compact=True
        )
        outs.append(out)
    t_disp = time.perf_counter() - t0
    np.asarray(outs[-1])
    t_all = time.perf_counter() - t0
    print(
        f"b2) {n} launches (h2d per launch):       dispatch {t_disp*1e3:7.2f} ms, "
        f"total {t_all*1e3:7.2f} ms  ({t_all/n*1e3:6.2f} ms/launch)"
    )

# same but passing raw numpy straight into the jitted call
for n in (1, 4, 8):
    t0 = time.perf_counter()
    outs = []
    for _ in range(n):
        table.state, out = gcra_scan(
            table.state, *pay, with_degen=False, compact=True
        )
        outs.append(out)
    t_disp = time.perf_counter() - t0
    np.asarray(outs[-1])
    t_all = time.perf_counter() - t0
    print(
        f"b3) {n} launches (numpy args direct):    dispatch {t_disp*1e3:7.2f} ms, "
        f"total {t_all*1e3:7.2f} ms  ({t_all/n*1e3:6.2f} ms/launch)"
    )

# ---- c) scan depth scaling ----------------------------------------------
for K in (16, 64, 128):
    payK = payload(K)
    devK = [jax.device_put(a, dev) for a in payK]
    jax.block_until_ready(devK)
    table.state, out = gcra_scan(
        table.state, *devK, with_degen=False, compact=True
    )
    out.block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(3):
        table.state, out = gcra_scan(
            table.state, *devK, with_degen=False, compact=True
        )
        out.block_until_ready()
    dt = (time.perf_counter() - t0) / 3
    print(
        f"c) scan K={K:4d}: {dt*1e3:7.2f} ms/launch blocked "
        f"({K*B/dt/1e6:6.2f} M dec/s)"
    )
