"""Round-5 differential fuzz campaign: the compact output-tier ladder
(w32 / cur / 4-plane) vs the scalar oracle, across every dispatch path.

Round 4's 1.5 M-request campaign targeted the batch/scan/wire/sharded
APIs; this one aims at what round 5 added — the w32 certificate's edges
and its cross-launch high-water marks:

  - params straddling the w32 field bounds (burst near 500-2100,
    tolerance near the 2047 s reset budget, retry near 1023 s);
  - big-tolerance keys that bump tol_hwm mid-stream and force later
    small-tol traffic down a tier;
  - tol >= 2^61 poison keys (cur_safe) mixed into the same stream;
  - degenerate probes (quantity 0), invalid lanes, duplicate segments,
    per-key param churn;
  - clock regressions (now stepping backward — the now_hwm guard);
  - mid-stream sweeps and snapshot save/restore (hwm recovery from
    restored TATs);

against single-device dispatch_many (native + python keymaps),
dispatch_wire_window (native prep + agg certificate), and the sharded
mesh dispatcher — all compared request-by-request to the scalar oracle
with the documented wire truncation (seconds, i32 saturation).

Usage: python scripts/fuzz_wire_tiers.py [--seeds N] [--steps M]
Exit 0 and a one-line tally on success; raises on first divergence.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

from throttlecrab_tpu.core.errors import CellError
from throttlecrab_tpu.core.rate_limiter import RateLimiter
from throttlecrab_tpu.core.store.periodic import PeriodicStore

NS = 1_000_000_000
T0 = 1_753_700_000 * NS
I32_MAX = (1 << 31) - 1

TOTAL = {"requests": 0, "windows": 0, "tiers": {"w32": 0, "cur": 0, "planes": 0}}


def draw_params(rng, profile):
    """One key's (burst, count, period).

    `profile` shapes the seed's traffic: "benign" stays inside the w32
    certificate (so whole streams ride the 4 B tier and its cross-launch
    bookkeeping), "edges" hugs the field bounds, "hostile" mixes in
    cur-only, poison (tol >= 2^61) and degenerate keys so the ladder
    keeps stepping down mid-stream.
    """
    r = rng.random()
    if profile == "benign":
        # em <= 1 s (count >= period) and burst <= 400 keeps tol within
        # ~400 s — comfortably inside every w32 field bound.
        period = int(rng.integers(1, 600))
        count = period * int(rng.integers(1, 120))
        return (int(rng.integers(2, 400)), count, period)
    if profile == "edges":
        if r < 0.6:
            # em = 1 s exactly; burst sweeps across the w32 reset
            # boundary (tol ~ 1024 s is where tol + hwm crosses 2047).
            period = int(rng.integers(1, 120))
            return (int(rng.integers(400, 2300)), period, period)
        period = int(rng.integers(1, 600))
        count = period * int(rng.integers(1, 120))
        return (int(rng.integers(2, 400)), count, period)
    # hostile
    if r < 0.25:
        return (int(rng.integers(2, 200)), int(rng.integers(1, 1000)),
                int(rng.integers(1, 600)))
    if r < 0.45:   # cur tier only (reset far past 2047 s)
        return (int(rng.integers(3000, 100_000)), 60, 60)
    if r < 0.58:   # tol >= 2^61 poison (4-plane + sticky cur_safe)
        return (3_000_000_000, 1, 1)
    if r < 0.72:   # degen material: burst 1 (tol 0)
        return (1, int(rng.integers(1, 50)), int(rng.integers(1, 60)))
    return (int(rng.integers(2, 50)), int(rng.integers(1, 3000)),
            int(rng.choice([1, 10, 60, 3600])))


def oracle_wire(oracle, keys, burst, count, period, qty, now_ns):
    n = len(keys)
    out = {
        "allowed": np.zeros(n, bool),
        "remaining": np.zeros(n, np.int64),
        "reset_s": np.zeros(n, np.int64),
        "retry_s": np.zeros(n, np.int64),
        "bad": np.zeros(n, bool),
    }
    for i in range(n):
        try:
            a, r = oracle.rate_limit(
                keys[i] if isinstance(keys[i], str) else keys[i].decode(),
                int(burst[i]), int(count[i]), int(period[i]), int(qty[i]),
                now_ns,
            )
        except CellError:
            out["bad"][i] = True
            continue
        out["allowed"][i] = a
        out["remaining"][i] = min(r.remaining, I32_MAX)
        out["reset_s"][i] = min(r.reset_after_ns // NS, I32_MAX)
        out["retry_s"][i] = min(r.retry_after_ns // NS, I32_MAX)
    return out


def check(res, want, ctx):
    ok = ~want["bad"]
    if not (np.asarray(res.status)[ok] == 0).all():
        raise AssertionError(f"{ctx}: unexpected status on valid lanes")
    for name, got in (
        ("allowed", np.asarray(res.allowed)),
        ("remaining", np.asarray(res.remaining)),
        ("reset_s", np.asarray(res.reset_after_s)),
        ("retry_s", np.asarray(res.retry_after_s)),
    ):
        g, w = got[ok], want[name][ok]
        if not (g == w).all():
            i = int(np.nonzero(g != w)[0][0])
            raise AssertionError(
                f"{ctx}: {name} diverged at valid lane {i}: "
                f"got {g[i]} want {w[i]}"
            )


def tier_of(handle):
    if getattr(handle, "_w32", False):
        return "w32"
    if getattr(handle, "_cur", False) or getattr(handle, "_now_list", None):
        return "cur"
    return "planes"


def run_seed(seed, steps, sharded_mesh, fused_alternate=False,
             insight_single=False):
    """One differential seed.

    `fused_alternate=True` flips THROTTLECRAB_PALLAS_FUSED between the
    fused Pallas kernel (interpret mode off-TPU) and the composed-XLA
    path on every step: both paths stay pinned to the scalar oracle
    request-by-request AND the table state each leaves behind must be
    one the other path continues from exactly — the cross-path
    stored-state compatibility the kill switch promises.
    `insight_single=True` arms the insight tier (INS_WIDTH rows) on the
    single-device limiter too, so the alternation covers both row-width
    templates of the fused kernel.
    """
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter
    from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

    rng = np.random.default_rng(seed)
    native = bool(seed % 2)
    try:
        lim = TpuRateLimiter(
            capacity=512, keymap="native" if native else "python",
            insight=insight_single,
        )
    except RuntimeError:
        lim = TpuRateLimiter(capacity=512, insight=insight_single)
        native = False
    if sharded_mesh is not None:
        from throttlecrab_tpu.parallel.sharded import ShardedTpuRateLimiter

        # Alternating seeds run the mesh with the insight tier armed
        # (INS_WIDTH shard rows + psum'd totals riding every launch):
        # the differential below then pins sharded+insight decisions to
        # the scalar oracle across the whole tier ladder, and the even
        # seeds keep pinning the 4-wide kill-switch layout.
        shl = ShardedTpuRateLimiter(
            capacity_per_shard=256, mesh=sharded_mesh,
            insight=bool(seed % 2),
        )
    else:
        shl = None
    oracle = RateLimiter(PeriodicStore())
    oracle_sh = RateLimiter(PeriodicStore())

    profile = ("benign", "edges", "hostile")[seed % 3]
    pool = [f"z{seed}x{i}" for i in range(int(rng.integers(4, 14)))]
    params = {k: draw_params(rng, profile) for k in pool}
    now = T0
    # Clock regressions must never cross a sweep or snapshot-restore
    # point: both drop entries expired AS OF that moment (exactly like
    # the reference's retain-based cleanup), while the bare-store
    # oracle expires on read and would "resurrect" them at an earlier
    # timestamp.  The engine is right; the comparison must respect the
    # drop point.
    floor_now = 0
    for step in range(steps):
        if fused_alternate:
            # Flip the dispatch per step: an XLA window, then a fused
            # window over the state the XLA one left, and so on.
            os.environ["THROTTLECRAB_PALLAS_FUSED"] = (
                "1" if step % 2 else "0"
            )
        # Occasional param churn, sweeps, clock moves (incl. regression).
        if rng.random() < 0.15:
            k = pool[rng.integers(len(pool))]
            params[k] = draw_params(rng, profile)
        if rng.random() < 0.12:
            jump = int(rng.integers(1, 7200)) * NS
            now += jump
            lim.sweep(now)
            if shl is not None:
                shl.sweep(now)
            floor_now = now
        # The oracle expires on read; only engines need explicit sweeps.
        n = int(rng.integers(2, 28))
        ks = [pool[rng.integers(len(pool))] for _ in range(n)]
        b = np.array([params[k][0] for k in ks], np.int64)
        c = np.array([params[k][1] for k in ks], np.int64)
        p = np.array([params[k][2] for k in ks], np.int64)
        # Quantity-0 probes appear in bursts on hostile streams only
        # (a single probe anywhere in a window forfeits the fast tiers).
        probe_p = 0.10 if profile == "hostile" else 0.0
        q = np.array(
            [0 if rng.random() < probe_p else 1 for _ in ks], np.int64
        )
        # windows of 1-3 batches through dispatch_many; each batch may
        # move the clock forward a little, or REGRESS it (now_hwm).
        batches = []
        wnow = now
        for _ in range(int(rng.integers(1, 4))):
            if rng.random() < 0.1:
                wnow = max(floor_now, wnow - int(rng.integers(1, 3 * NS)))
            batches.append((ks, b, c, p, q, wnow))
            wnow += int(rng.integers(0, NS))
        h = lim.dispatch_many(batches, wire=True)
        TOTAL["tiers"][tier_of(h)] += 1
        got = h.fetch()
        for bt, g in zip(batches, got):
            want = oracle_wire(oracle, *bt)
            check(g, want, f"seed{seed} step{step} single")
            TOTAL["requests"] += len(bt[0])
        TOTAL["windows"] += 1

        if shl is not None:
            h2 = shl.dispatch_many(batches, wire=True)
            TOTAL["tiers"][tier_of(h2)] += 1
            got2 = h2.fetch()
            for bt, g in zip(batches, got2):
                want = oracle_wire(oracle_sh, *bt)
                check(g, want, f"seed{seed} step{step} sharded")
                TOTAL["requests"] += len(bt[0])
            TOTAL["windows"] += 1
        now = wnow

        # Native wire window (agg certificate) every few steps.
        if native and step % 3 == 0 and hasattr(lim.keymap, "prepare_batch"):
            ks2 = [k.encode() for k in ks]
            blob = b"".join(ks2)
            offs = np.cumsum([0] + [len(k) for k in ks2]).astype(np.int64)
            pr = np.stack([b, c, p, q], axis=1)
            hw = lim.dispatch_wire_window([(blob, offs, pr)], now)
            if hw is not None:
                res = hw.fetch()[0]
                want = oracle_wire(oracle, ks, b, c, p, q, now)
                check(res, want, f"seed{seed} step{step} native-wire")
                TOTAL["requests"] += len(ks)
                TOTAL["windows"] += 1
            now += int(rng.integers(0, NS))

        # Mid-stream snapshot round trip (hwm recovery) occasionally.
        if step == steps // 2 and rng.random() < 0.5:
            with tempfile.TemporaryDirectory() as d:
                path = os.path.join(d, "fz")
                save_snapshot(lim, path)
                lim2 = TpuRateLimiter(
                    capacity=512,
                    keymap="native" if native else "python",
                    insight=insight_single,
                )
                load_snapshot(lim2, path + ".npz", now_ns=now)
                lim = lim2
                floor_now = now
    if fused_alternate:
        # Leave the process with the kill switch engaged (test callers
        # additionally restore the exact prior value via monkeypatch).
        os.environ["THROTTLECRAB_PALLAS_FUSED"] = "0"


def run_hotkey_deny_seed(seed, steps):
    """Hot-key abuse traffic (harness workload `hotkey-abuse`) through
    the front tier's deny cache: every per-request decision — status,
    allowed, limit, remaining, reset, retry — must be identical with the
    cache on and off, across param churn, clock jumps and sweeps.  The
    cache must also actually serve (hits > 0), or the equality is
    vacuous.  Returns the deny-cache hit count."""
    import asyncio

    from throttlecrab_tpu.front import DenyCache, FrontTier
    from throttlecrab_tpu.harness.workload import make_keys
    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.types import ThrottleRequest
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    rng = np.random.default_rng(seed)
    clock = {"now": T0}
    window = 24
    keys = make_keys("hotkey-abuse", steps * window, 2000, seed=seed)
    # Tight limits with slow emission so the hot keys saturate fast and
    # stay denied across windows — the deny cache's serving regime.
    key_params = {
        k: (int(rng.integers(2, 6)), int(rng.integers(1, 5)),
            int(rng.integers(10, 90)))
        for k in set(keys)
    }

    def norm(r):
        if isinstance(r, Exception):
            return (type(r).__name__, str(r))
        return (r.allowed, r.limit, r.remaining, r.reset_after,
                r.retry_after)

    async def run():
        front = FrontTier(DenyCache(4096), None)
        eng_on = BatchingEngine(
            TpuRateLimiter(capacity=512), batch_size=32, max_linger_us=200,
            now_fn=lambda: clock["now"], front=front,
        )
        eng_off = BatchingEngine(
            TpuRateLimiter(capacity=512), batch_size=32, max_linger_us=200,
            now_fn=lambda: clock["now"],
        )
        for step in range(steps):
            if rng.random() < 0.10:  # param churn on a random key
                k = keys[int(rng.integers(len(keys)))]
                key_params[k] = (
                    int(rng.integers(2, 6)), int(rng.integers(1, 5)),
                    int(rng.integers(10, 90)),
                )
            reqs = []
            for k in keys[step * window : (step + 1) * window]:
                burst, count, period = key_params[k]
                q = 0 if rng.random() < 0.02 else 1
                reqs.append(ThrottleRequest(k, burst, count, period, q))
            got_on, got_off = await asyncio.gather(
                asyncio.gather(
                    *[eng_on.throttle(r) for r in reqs],
                    return_exceptions=True,
                ),
                asyncio.gather(
                    *[eng_off.throttle(r) for r in reqs],
                    return_exceptions=True,
                ),
            )
            for i, (a, b) in enumerate(zip(got_on, got_off)):
                if norm(a) != norm(b):
                    raise AssertionError(
                        f"hotkey seed{seed} step{step} row {i} "
                        f"({reqs[i]}): cache-on {norm(a)} != "
                        f"cache-off {norm(b)}"
                    )
            TOTAL["requests"] += 2 * len(reqs)
            TOTAL["windows"] += 2
            clock["now"] += int(rng.integers(0, 3 * NS))
            if rng.random() < 0.06:  # expiry jump: vacate buckets
                clock["now"] += int(rng.integers(120, 600)) * NS
        await eng_on.shutdown()
        await eng_off.shutdown()
        return front.deny_cache.hits

    return asyncio.run(run())


def run_cluster_frame_fuzz(seed, iters):
    """Malformed-frame hardening for every elastic-cluster wire op:
    random truncations, byte flips and splices of valid frames must
    either decode cleanly or raise the typed ClusterProtocolError —
    never OverflowError/MemoryError/IndexError/struct.error, and never
    size an allocation from an attacker-controlled count.

    The mutation corpus is keyed off cluster.FRAME_DECODERS — the
    protocol's single source of truth — with one maker arm per OP_*
    constant.  A new op that lands without an arm here fails both the
    runtime sync assert below and, structurally, the wire-surface
    invariant checker (throttlecrab_tpu/analysis/wire_surface.py).
    Returns the number of frames exercised."""
    from throttlecrab_tpu.parallel.cluster import (
        FRAME_DECODERS,
        OP_DROUTE_BATCH,
        OP_JOIN,
        OP_LEAVE,
        OP_MIGRATE,
        OP_REPLICA,
        OP_RING,
        OP_RING_STATE,
        OP_ROUTE_BATCH,
        OP_THROTTLE_BATCH,
        OP_THROTTLE_REPLY,
        ClusterProtocolError,
        encode_batch,
        encode_droute,
        encode_join,
        encode_leave,
        encode_reply,
        encode_ring,
        encode_route,
        encode_rows,
    )

    rng = np.random.default_rng(seed)

    def mk_keys(n):
        return [
            bytes(rng.integers(0, 256, int(rng.integers(0, 40)),
                               dtype=np.uint8))
            for _ in range(n)
        ]

    def mk_params(n):
        return [
            tuple(int(x) for x in rng.integers(-(2**40), 2**40, 4))
            for _ in range(n)
        ]

    def mk_rows(op):
        n = int(rng.integers(0, 12))
        return encode_rows(
            op, int(rng.integers(0, 8)), int(rng.integers(0, 2**32)),
            mk_keys(n),
            rng.integers(-(2**62), 2**62, n),
            rng.integers(-(2**62), 2**62, n),
        )

    def mk_ring(op):
        return encode_ring(
            op, int(rng.integers(0, 2**32)),
            rng.random(int(rng.integers(0, 8))).tolist(),
        )

    def mk_batch(_op):
        n = int(rng.integers(0, 12))
        return encode_batch(
            mk_keys(n), mk_params(n), int(rng.integers(0, 2**62))
        )

    def mk_route(_op):
        n = int(rng.integers(0, 12))
        return encode_route(
            mk_keys(n), mk_params(n), int(rng.integers(0, 2**62)),
            int(rng.integers(0, 8)),
        )

    def mk_droute(_op):
        n = int(rng.integers(0, 12))
        return encode_droute(
            mk_keys(n), mk_params(n), int(rng.integers(0, 2**62)),
            int(rng.integers(0, 8)),
            rng.integers(-(2**62), 2**62, n),
        )

    def mk_reply(_op):
        n = int(rng.integers(0, 12))
        return encode_reply(
            rng.integers(0, 7, n), rng.integers(0, 2, n),
            rng.integers(-(2**62), 2**62, n),
            rng.integers(-(2**62), 2**62, n),
            rng.integers(0, 2**62, n), rng.integers(0, 2**62, n),
        )

    makers = {
        OP_THROTTLE_BATCH: mk_batch,
        OP_THROTTLE_REPLY: mk_reply,
        OP_MIGRATE: mk_rows,
        OP_RING: mk_ring,
        OP_JOIN: lambda _op: encode_join(int(rng.integers(0, 256))),
        OP_RING_STATE: mk_ring,
        OP_REPLICA: mk_rows,
        OP_ROUTE_BATCH: mk_route,
        OP_LEAVE: lambda _op: encode_leave(
            int(rng.integers(0, 256)), int(rng.integers(0, 2**32))
        ),
        OP_DROUTE_BATCH: mk_droute,
    }
    missing = set(FRAME_DECODERS) - set(makers)
    extra = set(makers) - set(FRAME_DECODERS)
    if missing or extra:
        raise SystemExit(
            f"fuzz arms out of sync with FRAME_DECODERS: "
            f"missing={sorted(missing)} extra={sorted(extra)}"
        )

    ops = sorted(makers)
    done = 0
    for _ in range(iters):
        op = ops[int(rng.integers(len(ops)))]
        frame = makers[op](op)
        decoder = FRAME_DECODERS[op][1]
        body = bytearray(frame[5:])  # strip _HDR, like the server does
        mode = rng.random()
        if mode < 0.35 and len(body):          # truncate
            body = body[: int(rng.integers(0, len(body)))]
        elif mode < 0.7 and len(body):         # flip bytes
            for _ in range(int(rng.integers(1, 4))):
                body[int(rng.integers(len(body)))] = int(
                    rng.integers(256)
                )
        elif mode < 0.85:                      # append garbage
            body += bytes(
                rng.integers(0, 256, int(rng.integers(1, 16)),
                             dtype=np.uint8)
            )
        try:
            decoder(bytes(body))
        except ClusterProtocolError:
            pass  # the typed rejection the wire contract promises
        done += 1
        TOTAL["requests"] += 1
    return done


def run_trace_frame_fuzz(seed, iters):
    """Malformed-frame hardening for the record/replay trace codec
    (throttlecrab_tpu/replay/trace.py): random truncations, byte flips,
    splices and explicit count-vs-size lies over valid traces must
    either decode cleanly or raise the typed TraceError — never
    struct.error/IndexError/MemoryError, and never size an allocation
    from an attacker-controlled count (a trace file is untrusted input:
    it may come off a crashed node or a bug report).  Returns the
    number of mutated inputs exercised."""
    import struct as _struct

    from throttlecrab_tpu.replay.trace import (
        _DECODERS,
        Trace,
        TraceError,
        TraceWriter,
    )

    rng = np.random.default_rng(seed)
    # Table-driven off the codec's own kind->decoder registry, so a new
    # REC_* kind is fuzzed the moment it is wired into _DECODERS.
    frame_decoders = tuple(fn for _, fn in sorted(_DECODERS.items()))
    done = 0
    for _ in range(iters):
        writer = TraceWriter()
        for _w in range(int(rng.integers(1, 4))):
            n = int(rng.integers(0, 10))
            keys = [
                bytes(rng.integers(0, 256, int(rng.integers(0, 24)),
                                   dtype=np.uint8))
                for _ in range(n)
            ]
            writer.add_window(
                int(rng.integers(0, 2**62)), int(rng.integers(0, 32)),
                keys,
                rng.integers(-(2**40), 2**40, (n, 4)),
                rng.integers(0, 2, n), rng.integers(0, 6, n),
                rng.integers(0, 2**16, n),
            )
        if rng.random() < 0.5:
            writer.add_event(
                int(rng.integers(0, 2**62)), "degrade", "x" * 5
            )
        if rng.random() < 0.5:
            writer.add_injection(
                "launch", "count", int(rng.integers(0, 1000)), 1.5
            )
        data = bytearray(writer.to_bytes())
        mode = rng.random()
        if mode < 0.30 and len(data) > 6:          # truncate
            data = data[: int(rng.integers(6, len(data)))]
        elif mode < 0.60 and len(data) > 6:        # flip bytes
            for _ in range(int(rng.integers(1, 5))):
                data[int(rng.integers(6, len(data)))] = int(
                    rng.integers(256)
                )
        elif mode < 0.75:                          # append garbage
            data += bytes(
                rng.integers(0, 256, int(rng.integers(1, 24)),
                             dtype=np.uint8)
            )
        elif mode < 0.9 and len(data) >= 6 + 5 + 13:
            # Explicit count-vs-size lie: overwrite the first window
            # frame's n field with a huge value (the decode_batch leak
            # class the PR-8 cluster fuzzer caught).
            _struct.pack_into(
                "<I", data, 6 + 5 + 9, int(rng.integers(2**20, 2**31))
            )
        try:
            Trace.loads(bytes(data))
        except TraceError:
            pass  # the typed rejection the trace contract promises
        # Bare frame bodies through each decoder (no file header).
        body = bytes(data[6:])
        dec = frame_decoders[int(rng.integers(len(frame_decoders)))]
        try:
            dec(body[: int(rng.integers(0, max(len(body), 1) + 1))])
        except TraceError:
            pass
        done += 1
        TOTAL["requests"] += 1
    return done


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=24)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--no-sharded", action="store_true")
    args = ap.parse_args()

    mesh = None
    if not args.no_sharded:
        from throttlecrab_tpu.parallel.sharded import make_mesh

        try:
            mesh = make_mesh(2)
        except ValueError:
            mesh = None
    for s in range(args.seeds):
        run_seed(3000 + s, args.steps, mesh)
        print(
            f"seed {3000 + s} ok — {TOTAL['requests']} requests, "
            f"tiers {TOTAL['tiers']}",
            file=sys.stderr, flush=True,
        )
    # Deny-cache differential: one hot-key abuse seed per ladder seed,
    # so fuzz campaigns exercise the front tier's exactness contract
    # under fresh param-churn/clock-jump interleavings (not just the
    # single CI-pinned seed).
    for s in range(args.seeds):
        hits = run_hotkey_deny_seed(4000 + s, args.steps * 2)
        print(
            f"hotkey seed {4000 + s} ok — {hits} deny-cache hits",
            file=sys.stderr, flush=True,
        )
    # Elastic-cluster wire hardening: mutated migrate/replica/ring/
    # route frames must fail typed, never crash.
    for s in range(args.seeds):
        n = run_cluster_frame_fuzz(5000 + s, args.steps * 40)
        print(
            f"cluster-frame seed {5000 + s} ok — {n} frames",
            file=sys.stderr, flush=True,
        )
    # Record/replay trace hardening: mutated trace files and bare
    # frames must fail with the typed TraceError, never crash.
    for s in range(args.seeds):
        n = run_trace_frame_fuzz(6000 + s, args.steps * 20)
        print(
            f"trace-frame seed {6000 + s} ok — {n} inputs",
            file=sys.stderr, flush=True,
        )
    print(
        f"PASS: {TOTAL['requests']} differential requests over "
        f"{TOTAL['windows']} windows; tier mix {TOTAL['tiers']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
