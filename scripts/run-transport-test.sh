#!/usr/bin/env bash
# End-to-end transport perf driver — the run-transport-test.sh equivalent
# (integration-tests/run-transport-test.sh): boots the server with every
# transport on high ports, runs the load generator per transport, then
# shuts the server down.
#
# Usage: scripts/run-transport-test.sh [-t http|grpc|redis|all] [-T workers]
#        [-r requests-per-worker] [--cpu]
set -euo pipefail

TRANSPORT=all
WORKERS=32
REQUESTS=1000
HTTP_PORT=58080
GRPC_PORT=58070
REDIS_PORT=58060
EXTRA_ENV=()

while [[ $# -gt 0 ]]; do
  case "$1" in
    -t) TRANSPORT="$2"; shift 2 ;;
    -T) WORKERS="$2"; shift 2 ;;
    -r) REQUESTS="$2"; shift 2 ;;
    --cpu) EXTRA_ENV+=("THROTTLECRAB_BENCH_CPU=1"); shift ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
done

cd "$(dirname "$0")/.."

PYBOOT='
import os
if os.environ.get("THROTTLECRAB_BENCH_CPU"):
    import jax; jax.config.update("jax_platforms", "cpu")
import sys
from throttlecrab_tpu.server.__main__ import main
sys.exit(main(sys.argv[1:]))
'

env "${EXTRA_ENV[@]}" python -c "$PYBOOT" \
    --http --http-port "$HTTP_PORT" \
    --grpc --grpc-port "$GRPC_PORT" \
    --redis --redis-port "$REDIS_PORT" \
    --store adaptive --log-level warn &
SERVER_PID=$!
trap 'kill "$SERVER_PID" 2>/dev/null || true' EXIT

# Wait for readiness via /health.
for _ in $(seq 1 120); do
  if curl -sf -m 1 "localhost:$HTTP_PORT/health" >/dev/null 2>&1; then
    break
  fi
  sleep 0.5
done
curl -sf -m 2 "localhost:$HTTP_PORT/health" >/dev/null

python -m throttlecrab_tpu.harness perf-test \
    --transport "$TRANSPORT" \
    --port "$HTTP_PORT" --grpc-port "$GRPC_PORT" --redis-port "$REDIS_PORT" \
    --workers "$WORKERS" --requests "$REQUESTS" --key-pattern zipfian

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
trap - EXIT
echo "done"
