#!/usr/bin/env bash
# End-to-end transport perf driver — the run-transport-test.sh equivalent
# (integration-tests/run-transport-test.sh): boots the server with every
# transport on high ports, runs the load generator per transport, then
# shuts the server down.
#
# Usage: scripts/run-transport-test.sh [-t http|grpc|redis|all] [-T workers]
#        [-r requests-per-worker] [--cpu] [--native] [--pipeline N]
#        [--procs N] [--warm N]
#
#   --native      use the C++ epoll backends for HTTP and RESP
#   --pipeline N  RESP only: N commands per pipelined write
#   --procs N     client worker processes (single-proc Python tops out
#                 around ~50K pipelined resp/s)
#   --warm N      per-transport warmup requests before the timed run
#                 (first-touch jit compiles take 10-40s on CPU)
set -euo pipefail

TRANSPORT=all
WORKERS=32
REQUESTS=1000
PIPELINE=1
PROCS=1
WARM=64
BACKEND=python
HTTP_PORT=58080
GRPC_PORT=58070
REDIS_PORT=58060
EXTRA_ENV=()

while [[ $# -gt 0 ]]; do
  case "$1" in
    -t) TRANSPORT="$2"; shift 2 ;;
    -T) WORKERS="$2"; shift 2 ;;
    -r) REQUESTS="$2"; shift 2 ;;
    --cpu) EXTRA_ENV+=("THROTTLECRAB_BENCH_CPU=1"); shift ;;
    --native) BACKEND=native; shift ;;
    --pipeline) PIPELINE="$2"; shift 2 ;;
    --procs) PROCS="$2"; shift 2 ;;
    --warm) WARM="$2"; shift 2 ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
done

cd "$(dirname "$0")/.."

PYBOOT='
import os
if os.environ.get("THROTTLECRAB_BENCH_CPU"):
    import jax; jax.config.update("jax_platforms", "cpu")
import sys
from throttlecrab_tpu.server.__main__ import main
sys.exit(main(sys.argv[1:]))
'

env "${EXTRA_ENV[@]}" python -c "$PYBOOT" \
    --http --http-port "$HTTP_PORT" --http-backend "$BACKEND" \
    --grpc --grpc-port "$GRPC_PORT" \
    --redis --redis-port "$REDIS_PORT" --redis-backend "$BACKEND" \
    --store adaptive --log-level warn &
SERVER_PID=$!
trap 'kill "$SERVER_PID" 2>/dev/null || true' EXIT

# Wait for readiness via /health.
for _ in $(seq 1 120); do
  if curl -sf -m 1 "localhost:$HTTP_PORT/health" >/dev/null 2>&1; then
    break
  fi
  sleep 0.5
done
curl -sf -m 2 "localhost:$HTTP_PORT/health" >/dev/null

# Warmup: drive every selected transport through the first-touch compiles
# so the timed run measures steady state, not XLA compilation.
if [[ "$WARM" -gt 0 ]]; then
  python -m throttlecrab_tpu.harness perf-test \
      --transport "$TRANSPORT" \
      --port "$HTTP_PORT" --grpc-port "$GRPC_PORT" \
      --redis-port "$REDIS_PORT" \
      --workers 4 --requests "$WARM" --key-pattern zipfian \
      >/dev/null
fi

python -m throttlecrab_tpu.harness perf-test \
    --transport "$TRANSPORT" \
    --port "$HTTP_PORT" --grpc-port "$GRPC_PORT" --redis-port "$REDIS_PORT" \
    --workers "$WORKERS" --requests "$REQUESTS" --key-pattern zipfian \
    --pipeline "$PIPELINE" --procs "$PROCS"

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
trap - EXIT
echo "done"
