"""Attribute the by-id kernel's device time on hardware.

The device-resident ceiling (bench.py) measures ~0.49 ms per 4096-request
micro-batch for the full by-id kernel.  This probe ablates the body —
id-row gather, state gather, math, scatter — with requests pre-staged on
device and outputs reduced to one scalar (one fetch per timing block), so
the numbers are device compute, not tunnel transfers.

Also the Pallas A/B: run with THROTTLECRAB_PALLAS=1 to route the state
row gather/scatter through the Pallas DMA kernels (tpu/pallas_ops.py) —
compare the `full` row against the default run.  --cpu forces the CPU
backend (interpret-mode Pallas; correctness only).
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import throttlecrab_tpu  # noqa: F401
import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from throttlecrab_tpu.tpu.kernel import (
    EMPTY_EXPIRY,
    _U32,
    _gcra_body,
    pack_id_rows,
    pack_state,
)

dev = jax.devices()[0]
print(f"device: {dev}  pallas={os.environ.get('THROTTLECRAB_PALLAS', '0')}",
      file=sys.stderr, flush=True)

B = 4096
K = 256
N_IDS = 1_000_000
CAP = 1 << 21
NOW = 1_753_000_000_000_000_000

_sum = jax.jit(lambda x: x.sum())


def make_scan(mode):
    @partial(jax.jit, donate_argnums=(0,))
    def scan(state, id_rows, words, now):
        n_ids = id_rows.shape[0]

        def join(lo, hi):
            return (hi.astype(jnp.int64) << 32) | (
                lo.astype(jnp.int64) & _U32
            )

        def step(state, kb):
            w, now_k = kb
            meta = w >> 32
            idx = jnp.clip((w & _U32).astype(jnp.int32), 0, n_ids - 1)
            if mode == "noidrow":
                # synthesize params arithmetically; slot = id
                slots = idx
                em = 20_000_000 + (idx.astype(jnp.int64) % 977) * 1000
                tol = em * 7
            else:
                rows = id_rows[idx]
                slots = rows[:, 0]
                em = join(rows[:, 1], rows[:, 2])
                tol = join(rows[:, 3], rows[:, 4])
            batch = (
                slots,
                meta & 0x3FFF,
                (meta & (1 << 14)) != 0,
                em,
                tol,
                jnp.full(w.shape, 1, jnp.int64),
                (meta & (1 << 15)) != 0,
                now_k,
            )
            if mode in ("full", "noidrow"):
                return _gcra_body(
                    state, batch, with_degen=False, compact="cur"
                )
            # hand-rolled reduced bodies for attribution
            (slots, rank, is_last, em, tol, qty, valid, now_k) = batch
            N = state.shape[0]
            s = jnp.clip(slots, 0, N - 1).astype(jnp.int32)
            if mode in ("nostate", "elementwise"):
                stored_tat = slots.astype(jnp.int64) * 1_000
                stored_exp = jnp.full_like(stored_tat, EMPTY_EXPIRY)
            else:
                from throttlecrab_tpu.tpu.kernel import unpack_state

                stored_tat, stored_exp = unpack_state(state[s])
            live = valid & (stored_exp > now_k)
            inc = em
            t0 = jnp.where(
                live,
                jnp.maximum(stored_tat, now_k - tol),
                now_k - em,
            )
            num = now_k + tol - t0
            m_raw = jnp.maximum(num // jnp.maximum(inc, 1), 0)
            allowed = (rank < m_raw) & valid
            cur = jnp.where(allowed, t0 + (rank + 1) * inc, t0 + m_raw * inc)
            out = cur * 2 + allowed.astype(jnp.int64)
            if mode in ("noscatter", "elementwise"):
                return state, out
            tat_fin = t0 + jnp.minimum(m_raw, rank + 1) * inc
            rows_w = pack_state(tat_fin, tat_fin + tol)
            wrote = (m_raw >= 1) & valid & is_last
            scratch = N - B + jnp.arange(B, dtype=jnp.int32)
            sidx = jnp.where(wrote, s, scratch).astype(jnp.int32)
            state = state.at[sidx].set(
                rows_w, unique_indices=True, mode="drop"
            )
            return state, out

        return jax.lax.scan(step, state, (words, now.astype(jnp.int64)))

    return scan


rng = np.random.default_rng(5)
kid = np.arange(N_IDS, dtype=np.int64)
em_all = 20_000_000 + (kid % 977) * 1000
tol_all = em_all * 7
slots_all = np.arange(N_IDS, dtype=np.int32)
id_rows = jax.device_put(pack_id_rows(slots_all, em_all, tol_all), dev)

# Pre-staged request words: Zipf-free uniform draw is fine for compute
# attribution (segment structure present via duplicates at 1M keys).
def stage():
    ids = rng.integers(0, N_IDS, (K, B)).astype(np.int64)
    meta = (1 << 14) | (1 << 15)  # rank 0, is_last, valid (dups rare)
    w = (np.int64(meta) << 32) | ids
    wd = jax.device_put(w, dev)
    np.asarray(_sum(wd))
    return wd


def make_state():
    return pack_state(
        jnp.zeros((CAP,), jnp.int64),
        jnp.full((CAP,), EMPTY_EXPIRY, jnp.int64),
    )


now = np.full(K, NOW, np.int64)
R = 4
for mode in ("full", "noidrow", "nostate", "noscatter", "elementwise"):
    scan = make_scan(mode)
    state = make_state()
    staged = [stage() for _ in range(R)]
    state, out = scan(state, id_rows, staged[0], now)
    np.asarray(_sum(out))  # compile + drain
    t0 = time.perf_counter()
    checks = []
    for wd in staged:
        state, out = scan(state, id_rows, wd, now)
        checks.append(_sum(out))
    np.asarray(sum(checks))
    dt = (time.perf_counter() - t0) / R
    print(
        f"{mode:12s}: {dt*1e3:8.2f} ms/launch  "
        f"({dt/K*1e3:6.3f} ms/batch, {K*B/dt/1e6:6.2f} M dec/s)",
        flush=True,
    )

# Width ablation: the kernels read only row columns 0-4, so the
# resident parameter gather can shrink 8 -> 5 i32 per id (32 -> 20 B).
# Whether the narrower gather buys anything depends on the chip's tile
# padding — measure, don't guess (round-4 idea list).
scan = make_scan("full")
for width in (8, 5):
    rows_w = jax.device_put(
        pack_id_rows(slots_all, em_all, tol_all, width=width), dev
    )
    state = make_state()
    staged = [stage() for _ in range(R)]
    state, out = scan(state, rows_w, staged[0], now)
    np.asarray(_sum(out))
    t0 = time.perf_counter()
    checks = []
    for wd in staged:
        state, out = scan(state, rows_w, wd, now)
        checks.append(_sum(out))
    np.asarray(sum(checks))
    dt = (time.perf_counter() - t0) / R
    print(
        f"width {width}     : {dt*1e3:8.2f} ms/launch  "
        f"({dt/K*1e3:6.3f} ms/batch, {K*B/dt/1e6:6.2f} M dec/s)",
        flush=True,
    )
