"""Is the tunnel full-duplex?  Measure h2d/d2h overlap (or its absence).

The launch cost model (docs/tpu-launch-profile.md, cited by
tpu/kernel.py and bench.py) rests on one claim: the relay link is
SERIALIZED — host→device uploads, device compute, and device→host
fetches share one ~15-50 MB/s pipe and do not overlap, so end-to-end
throughput ≈ link_rate / (h2d_bytes + d2h_bytes per request).  This
probe measures that claim directly:

  1. h2d alone      — time N uploads of M MB.
  2. d2h alone      — time N fetches of M MB (never-fetched buffers).
  3. h2d ∥ d2h      — run both streams concurrently from two threads.

On a full-duplex link the concurrent wall time ≈ max(h2d, d2h); on a
serialized link it ≈ h2d + d2h.  Round 4 measured the serialized case:
concurrent wall time within a few percent of the sum, h2d ~40-50 MB/s,
first-fetch d2h ~10-30 MB/s (single-stream), establishing the
bytes-per-request budget that drove the by-id (4-8 B/request up) and
compact="cur" (8 B/request down) launch modes.

Usage: python scripts/probe_duplex.py [--cpu] [--mb M] [--n N]
Run on a healthy tunnel (never timeout-kill it mid-claim).
"""

from __future__ import annotations

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import throttlecrab_tpu  # noqa: F401  (repo-root import side effects)
import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")


def arg(flag: str, default: int) -> int:
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return default


MB = arg("--mb", 8)
N = arg("--n", 6)

dev = jax.devices()[0]
print(f"device: {dev}  ({MB} MB x {N} buffers per stream)", flush=True)

n_el = MB * (1 << 20) // 4
mk = jax.jit(lambda x: x * 3 + 1)


def fresh_device_outputs(n):
    """n distinct never-fetched device buffers (fetch cost is paid on
    first materialization; reusing a fetched buffer would measure a
    cache, not the link)."""
    outs = []
    for i in range(n):
        seed = jax.device_put(np.arange(n_el, dtype=np.int32) + i, dev)
        outs.append(mk(seed))
    for o in outs:
        o.block_until_ready()
    return outs


def host_buffers(n):
    return [np.arange(n_el, dtype=np.int32) + 7 * i for i in range(n)]


def run_h2d(bufs):
    t = time.perf_counter()
    put = [jax.device_put(b, dev) for b in bufs]
    for p in put:
        p.block_until_ready()
    return time.perf_counter() - t


def run_d2h(outs):
    t = time.perf_counter()
    for o in outs:
        np.asarray(o)
    return time.perf_counter() - t


def report(label, secs, mbytes):
    print(f"{label:<18} {secs * 1e3:8.1f} ms   {mbytes / secs:7.1f} MB/s",
          flush=True)


# Warm-up: the first timing block in a process reads ~2x slow through the
# relay (docs/tpu-launch-profile.md); one throwaway round of each.
run_h2d(host_buffers(2))
run_d2h(fresh_device_outputs(2))

total_mb = MB * N

t_up = run_h2d(host_buffers(N))
report("h2d alone", t_up, total_mb)

t_down = run_d2h(fresh_device_outputs(N))
report("d2h alone", t_down, total_mb)

# Concurrent streams: prepare both sides first so neither setup is timed.
outs = fresh_device_outputs(N)
bufs = host_buffers(N)
pool = ThreadPoolExecutor(2)
t = time.perf_counter()
f_up = pool.submit(run_h2d, bufs)
f_down = pool.submit(run_d2h, outs)
f_up.result(), f_down.result()
t_both = time.perf_counter() - t
report("h2d ∥ d2h", t_both, 2 * total_mb)

serial = t_up + t_down
overlap = max(t_up, t_down)
print(
    f"\nserialized-link prediction {serial * 1e3:.1f} ms, full-duplex "
    f"prediction {overlap * 1e3:.1f} ms, measured {t_both * 1e3:.1f} ms",
    flush=True,
)
ratio = (t_both - overlap) / max(serial - overlap, 1e-9)
print(
    f"serialization ratio {ratio:.2f}  "
    "(1.0 = fully serialized, 0.0 = full duplex)",
    flush=True,
)
