"""CI replay-determinism gate: record a short CPU workload through the
real batching engine with the flight recorder armed, then replay the
captured trace twice against fresh limiters and byte-diff the outcome
vectors.

Three contracts, each a hard failure:

1. two replays of one trace are byte-identical (determinism);
2. the replayed outcomes are byte-identical to the *recorded* outcomes
   (capture fidelity: the trace really carries the decisions made);
3. the replayed outcomes match the scalar oracle row-for-row
   (differential: replay drift vs the ground-truth engine is a bug).

Usage: python scripts/replay_determinism.py [--windows N]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax

jax.config.update("jax_platforms", "cpu")

NS = 1_000_000_000
T0 = 1_753_700_000 * NS


async def record_workload(trace_path: str, windows: int) -> None:
    from throttlecrab_tpu.harness.workload import make_keys
    from throttlecrab_tpu.replay.recorder import (
        FlightRecorder,
        arm,
        disarm,
    )
    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.types import ThrottleRequest
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    recorder = FlightRecorder(
        mode="full", out_dir=os.path.dirname(trace_path),
        path=trace_path,
    )
    arm(recorder)
    try:
        clock = {"now": T0}
        engine = BatchingEngine(
            TpuRateLimiter(capacity=4096),
            batch_size=64,
            max_linger_us=200,
            now_fn=lambda: clock["now"],
        )
        keys = make_keys("hotkey-abuse", windows * 64, 2000, seed=11)
        for step in range(windows):
            reqs = [
                ThrottleRequest(k, 4, 10, 60, 1)
                for k in keys[step * 64: (step + 1) * 64]
            ]
            await asyncio.gather(
                *[engine.throttle(r) for r in reqs],
                return_exceptions=True,
            )
            clock["now"] += NS // 2
        await engine.shutdown()
    finally:
        recorder.close()
        disarm()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--windows", type=int, default=24)
    args = ap.parse_args()

    from throttlecrab_tpu.replay.player import (
        differential_replay,
        make_target,
        outcome_vector,
        replay,
    )
    from throttlecrab_tpu.replay.trace import Trace

    with tempfile.TemporaryDirectory() as d:
        trace_path = os.path.join(d, "ci.tctr")
        asyncio.run(record_workload(trace_path, args.windows))
        trace = Trace.load(trace_path)
        assert trace.windows, "recorder captured no windows"

        v1 = outcome_vector(replay(trace, make_target("device", trace)))
        v2 = outcome_vector(replay(trace, make_target("device", trace)))
        if v1 != v2:
            print("FAIL: two replays diverged byte-wise", file=sys.stderr)
            return 1
        if v1 != trace.outcome_vector():
            print(
                "FAIL: replayed outcomes differ from recorded outcomes",
                file=sys.stderr,
            )
            return 1
        report = differential_replay(trace, "device")
        if not report.ok:
            for m in (report.vs_oracle + report.vs_recorded)[:16]:
                print(str(m), file=sys.stderr)
            print("FAIL: differential replay mismatches", file=sys.stderr)
            return 1
        print(
            f"PASS: {len(trace.windows)} windows / {trace.n_rows()} rows "
            "— replay x2 byte-identical, recorded-equal, oracle-exact"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
