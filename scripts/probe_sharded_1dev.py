"""Re-check the round-4 known issue: sharded scan on a 1-device REAL mesh.

One observed real-v5e run of the cross-batch state-carry scenario failed
its assertion on a silently-degraded 1-device TPU mesh (TESTING.md
"Known issue"), while CPU meshes of every size pass.  This script runs
the exact scenario on whatever real backend the environment provides
(mesh of 1) plus the non-sharded twin, and prints a verdict — run it
first thing on a healthy tunnel:

    nohup python scripts/probe_sharded_1dev.py > /tmp/sharded1.out 2>&1 &

(NEVER run a TPU claimant under `timeout` — a killed claimant wedges
the relay.)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import throttlecrab_tpu  # noqa: F401
import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

from throttlecrab_tpu.parallel.sharded import ShardedTpuRateLimiter, make_mesh
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

T0 = 1_700_000_000 * 10**9


def scenario(lim):
    batches = [(["hot"] * 4, 10, 100, 3600, 1, T0 + k) for k in range(4)]
    results = lim.rate_limit_many(batches)
    return [bool(a) for r in results for a in r.allowed]


def main() -> int:
    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr, flush=True)
    want = [True] * 10 + [False] * 6

    sharded = ShardedTpuRateLimiter(
        capacity_per_shard=64, mesh=make_mesh(1)
    )
    got_sharded = scenario(sharded)

    plain = TpuRateLimiter(capacity=64)
    got_plain = scenario(plain)

    print(json.dumps({
        "platform": dev.platform,
        "sharded_1dev_ok": got_sharded == want,
        "plain_ok": got_plain == want,
        "sharded_allowed": got_sharded,
        "sharded_counters": [sharded.total_allowed, sharded.total_denied],
    }))
    return 0 if got_sharded == want and got_plain == want else 1


if __name__ == "__main__":
    sys.exit(main())
