"""The minimum-wire-bytes launch path: raw key ids against
device-resident parameter rows.

This is the API behind bench.py's headline number (see
docs/tpu-launch-profile.md): when the key universe and its limits are
known up front — the common serving shape: per-tenant/per-user configs —
each decision costs 4 bytes up (the i32 key id; the device derives the
duplicate-segment structure itself) and 8 bytes down (one i64
`cur*2+allowed` word, completed to the exact i32 wire values by C++
tk_finish_raw).  On a link-bound accelerator that is the difference
between 0.36 and 5+ million decisions/s.

The round-5 tiers shrink both directions further when their
certificates hold — 20-bit packed ids (2.5 B/request up, tables under
2^20 − 1 keys) and the `w32` output (4 B/request down, the device
packs the exact wire values) — shown at the end.

Runs on whatever backend JAX provides (TPU if available, CPU otherwise).
"""

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

if "--cpu" in _s.argv:
    # In-process pin: the JAX_PLATFORMS env var alone is not honored
    # once an accelerator PJRT plugin registered via sitecustomize, and
    # a first device touch on a wedged serving tunnel hangs forever.
    import jax

    jax.config.update("jax_platforms", "cpu")

import time

import numpy as np

from throttlecrab_tpu.tpu.limiter import TpuRateLimiter, derive_params


def main() -> None:
    limiter = TpuRateLimiter(capacity=1 << 16, keymap="native")
    km, table = limiter.keymap, limiter.table

    # ---- setup (once): intern the key universe, upload its limits ----
    n_keys = 10_000
    keys = [b"tenant:%d/user:%d" % (i % 64, i) for i in range(n_keys)]
    kid = np.arange(n_keys, dtype=np.int64)
    burst = 5 + (kid % 20)
    count = 50 + (kid % 500)
    period = 30 + (kid % 90)
    em, tol, invalid = derive_params(burst, count, period)
    assert not invalid.any()

    km.intern(keys)
    rows = table.upload_id_rows(km.resolve_all(), em, tol, keymap=km)

    # ---- steady state: ship NOTHING but ids -------------------------
    now = time.time_ns()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, n_keys, 4096).astype(np.int32)
    cur2 = np.asarray(
        table.check_many_ids(
            rows, ids.reshape(1, 4096), np.array([now], np.int64),
            quantity=1, with_degen=False, compact="cur",
        )
    ).reshape(-1)
    wire = km.finish_raw(ids, em, tol, 1, cur2, now)
    allowed, remaining = wire[:, 0], wire[:, 1]
    print(
        f"decided {len(ids)} requests: {int(allowed.sum())} allowed; "
        f"remaining[0..4] = {remaining[:4].tolist()}"
    )

    # Hot key inside one launch: exact sequential burst semantics, with
    # the duplicate-segment structure derived on the device.
    hot_id = np.full(64, 7, np.int32)
    cur2 = np.asarray(
        table.check_many_ids(
            rows, hot_id.reshape(1, 64), np.array([now], np.int64),
            quantity=1, with_degen=False, compact="cur",
        )
    ).reshape(-1)
    wire = km.finish_raw(hot_id, em, tol, 1, cur2, now)
    print(
        f"hot key: {int(wire[:, 0].sum())}/64 allowed "
        f"(burst {int(burst[7])}, minus any tokens the random batch "
        f"above already spent on id 7)"
    )

    # ---- round-5 minimum: 2.5 B up, 4 B down ------------------------
    # 20-bit packed ids + the w32 device-packed wire word.  fits_w32_wire
    # certifies this key universe (small tolerances), so the unpack is
    # three shifts — no reconstruction arithmetic at all.
    from throttlecrab_tpu.tpu.kernel import (
        finish_w32,
        fits_w32_wire,
        pack_ids20,
    )

    assert fits_w32_wire(
        np.ones(n_keys, bool), em, tol, np.ones(n_keys, np.int64),
        now, table.tol_hwm, table.now_hwm,
    )
    ids2 = rng.integers(0, n_keys, 4096).astype(np.int32)
    w = np.asarray(
        table.check_many_ids20(
            rows, pack_ids20(ids2.reshape(1, 4096)),
            np.array([now + 1_000_000], np.int64),
            quantity=1, with_degen=False, compact="w32",
        )
    ).reshape(-1)
    allowed, remaining, reset_s, retry_s = finish_w32(w)
    print(
        f"ids20+w32 (6.5 B/request): {int(allowed.sum())} allowed; "
        f"reset_s[0..4] = {reset_s[:4].tolist()}"
    )


if __name__ == "__main__":
    main()
