"""Access-pattern demo: how key locality shapes batched throughput.

The reference's examples/access_patterns.rs walks Sequential / Random /
Hot-Key (90/10) / Zipfian key streams one request at a time; here the same
four patterns flow through the batched TPU engine — the interesting
comparison is decisions/s per *pattern*, since the closed-form kernel
serializes duplicate keys inside a batch without any sort or scan.

Run: python examples/access_patterns.py [--cpu]
"""

from __future__ import annotations

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import sys
import time

import numpy as np

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
N_KEYS = 10_000
BATCH = 1024
BATCHES = 64


def pattern_keys(name: str, rng) -> list:
    n = BATCH * BATCHES
    if name == "sequential":
        ids = np.arange(n) % N_KEYS
    elif name == "random":
        ids = rng.integers(0, N_KEYS, n)
    elif name == "hot_key":
        # 90% of traffic on 10% of keys.
        hot = rng.integers(0, N_KEYS // 10, n)
        cold = rng.integers(0, N_KEYS, n)
        ids = np.where(rng.random(n) < 0.9, hot, cold)
    elif name == "zipfian":
        ranks = np.arange(1, N_KEYS + 1, dtype=np.float64)
        p = ranks ** -1.1
        p /= p.sum()
        ids = rng.choice(N_KEYS, n, p=p)
    else:
        raise ValueError(name)
    return [f"key_{int(i)}" for i in ids]


def main() -> None:
    t0 = 1_753_000_000 * NS
    for name in ("sequential", "random", "hot_key", "zipfian"):
        rng = np.random.default_rng(42)
        limiter = TpuRateLimiter(capacity=1 << 15)
        keys = pattern_keys(name, rng)
        # Warm (compiles the kernel for this shape).
        limiter.rate_limit_batch(keys[:BATCH], 100, 1000, 3600, 1, t0)
        start = time.perf_counter()
        allowed = 0
        for b in range(BATCHES):
            res = limiter.rate_limit_batch(
                keys[b * BATCH : (b + 1) * BATCH],
                100, 1000, 3600, 1, t0 + b * 1_000_000,
                wire=True,
            )
            allowed += int(res.allowed.sum())
        dt = time.perf_counter() - start
        print(
            f"{name:>10}: {BATCH * BATCHES / dt:>12,.0f} decisions/s  "
            f"({allowed} allowed, {len(limiter)} live keys)"
        )


if __name__ == "__main__":
    main()
