"""Capacity-pressure demo: table growth, sweep reclamation, memory/key.

The reference's examples/capacity_test.rs pushes unique keys through each
store to show capacity behavior (docs/capacity-behavior.md).  The TPU
table is dense SoA — 16 bytes of HBM per slot — so the interesting
behavior is growth doubling (HashMap-style) and the expiry sweep
vacating slots for the host keymap to reuse.

Run: python examples/capacity_test.py [--cpu]
"""

from __future__ import annotations

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import sys

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_753_000_000 * NS


def main() -> None:
    limiter = TpuRateLimiter(capacity=1024, auto_grow=True)
    print(f"initial capacity: {limiter.total_capacity} slots "
          f"({limiter.total_capacity * 16 / 1024:.0f} KiB HBM)")

    # 1. Push 10x the initial capacity of unique short-TTL keys.
    n = 10_240
    for start in range(0, n, 1024):
        keys = [f"burst_key_{i}" for i in range(start, start + 1024)]
        limiter.rate_limit_batch(keys, 10, 100, 60, 1, T0)  # 60 s period
    print(f"after {n} unique keys: capacity={limiter.total_capacity}, "
          f"live={len(limiter)}")

    # 2. Everything expires after its TTL; one sweep vacates the slots.
    freed = limiter.sweep(T0 + 3600 * NS)
    print(f"sweep at +1h freed {freed} slots; live={len(limiter)}")

    # 3. The vacated capacity is reused without further growth.
    before = limiter.total_capacity
    for start in range(0, n, 1024):
        keys = [f"second_wave_{i}" for i in range(start, start + 1024)]
        limiter.rate_limit_batch(keys, 10, 100, 60, 1, T0 + 3601 * NS)
    print(f"second wave of {n} keys reused slots: capacity "
          f"{before} -> {limiter.total_capacity} (no growth)")

    hbm = limiter.total_capacity * 16
    print(
        f"\nmemory model: {hbm / 1024:.0f} KiB HBM for "
        f"{limiter.total_capacity} slots (16 B/slot) + host keymap "
        "(~60 B + key bytes per live key)"
    )


if __name__ == "__main__":
    main()
