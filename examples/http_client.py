"""Call a running throttlecrab-tpu server over HTTP/JSON — the
client-side example every protocol has (reference:
throttlecrab-server/examples/http_client.rs:1-92).

Start a server first:
    python -m throttlecrab_tpu.server --http --http-port 9090

Then:
    python examples/http_client.py [--url http://127.0.0.1:9090]

Uses only the standard library, so it doubles as the copy-paste snippet
for services without an HTTP client dependency.
"""

from __future__ import annotations

import argparse
import json
import urllib.error
import urllib.request


def throttle(
    base_url: str,
    key: str,
    max_burst: int,
    count_per_period: int,
    period: int,
    quantity: int = 1,
) -> dict:
    """One rate-limit decision.  Returns the response dict:
    {"allowed", "limit", "remaining", "reset_after", "retry_after"}."""
    req = urllib.request.Request(
        f"{base_url}/throttle",
        data=json.dumps(
            {
                "key": key,
                "max_burst": max_burst,
                "count_per_period": count_per_period,
                "period": period,
                "quantity": quantity,
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default="http://127.0.0.1:9090")
    args = ap.parse_args()

    print("Basic rate limiting (burst 10):")
    for i in range(12):
        r = throttle(args.url, "user:456", 10, 20, 60)
        verdict = "allowed" if r["allowed"] else (
            f"DENIED (retry after {r['retry_after']}s)"
        )
        print(f"  request {i + 1:2d}: {verdict}  remaining={r['remaining']}")

    print("\nPer-key isolation:")
    for key in ("user:1", "user:2", "user:1"):
        r = throttle(args.url, key, 3, 10, 60)
        print(f"  {key}: allowed={r['allowed']} remaining={r['remaining']}")

    print("\nCost > 1 (quantity=5 against burst 10):")
    for i in range(3):
        r = throttle(args.url, "bulk:job", 10, 100, 60, quantity=5)
        print(f"  request {i + 1}: allowed={r['allowed']} "
              f"remaining={r['remaining']}")

    print("\nServer health + metrics:")
    with urllib.request.urlopen(f"{args.url}/health", timeout=5) as resp:
        print(f"  /health -> {resp.read().decode()}")
    with urllib.request.urlopen(f"{args.url}/metrics", timeout=5) as resp:
        lines = resp.read().decode().splitlines()
        wanted = [ln for ln in lines if ln.startswith("throttlecrab_requests")]
        for ln in wanted[:4]:
            print(f"  {ln}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
