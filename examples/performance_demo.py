"""In-process performance demo — the reference's
examples/performance_demo.rs equivalent, adapted to the batched engine:
the scalar-compat API decides one request per call (paying a device
launch each), the batch API amortizes one launch over thousands.

    python examples/performance_demo.py [--cpu] [--batch 4096]
"""

from __future__ import annotations

import argparse
import os.path as _p
import sys as _s
import time

_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import numpy as np


def demo_scalar(limiter, now_ns: int, iterations: int = 2_000) -> None:
    print("\nScalar API (one device launch per decision)")
    print("-" * 44)
    for i in range(100):  # warm the compile
        limiter.rate_limit(f"warm_{i}", 100, 1000, 60, 1, now_ns)
    t0 = time.perf_counter()
    for i in range(iterations):
        limiter.rate_limit(
            f"bench_key_{i % 1000}", 100, 1000, 60, 1, now_ns + i * 1000
        )
    dt = time.perf_counter() - t0
    print(f"{iterations} decisions in {dt:.2f}s -> "
          f"{iterations / dt:,.0f} req/s "
          f"({dt / iterations * 1e6:.1f} us/req)")


def demo_batched(limiter, now_ns: int, batch: int, iters: int = 64) -> None:
    print(f"\nBatch API ({batch} decisions per launch)")
    print("-" * 44)
    keys = [f"bench_key_{i}" for i in range(10_000)]
    rng = np.random.default_rng(1)
    sel = rng.integers(0, len(keys), (iters + 1, batch))
    limiter.rate_limit_batch(  # warm the compile
        [keys[i] for i in sel[0]], 100, 1000, 60, 1, now_ns
    )
    t0 = time.perf_counter()
    for it in range(1, iters + 1):
        limiter.rate_limit_batch(
            [keys[i] for i in sel[it]], 100, 1000, 60, 1,
            now_ns + it * 1_000_000,
        )
    dt = time.perf_counter() - t0
    total = iters * batch
    print(f"{total} decisions in {dt:.2f}s -> {total / dt:,.0f} req/s "
          f"({dt / total * 1e9:.0f} ns/req)")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--batch", type=int, default=4096)
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    print("throttlecrab-tpu Performance Demo")
    print("=" * 44)

    now_ns = time.time_ns()
    limiter = TpuRateLimiter(capacity=1 << 15, keymap="auto")
    demo_scalar(limiter, now_ns)
    demo_batched(limiter, now_ns, args.batch)

    print("\nThe gap is the whole design: the reference amortizes a "
          "HashMap lookup per call,\nthis framework amortizes a device "
          "launch per *batch* (see bench.py for the\nfull serving-path "
          "number with pipelined launches).")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
