"""Batched device-engine use: thousands of decisions per launch.

Runs on whatever backend JAX provides (TPU if available, CPU otherwise).
"""

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

if "--cpu" in _s.argv:
    # In-process pin: the JAX_PLATFORMS env var alone is not honored
    # once an accelerator PJRT plugin registered via sitecustomize, and
    # a first device touch on a wedged serving tunnel hangs forever.
    import jax

    jax.config.update("jax_platforms", "cpu")

import time

import numpy as np

from throttlecrab_tpu.tpu.limiter import TpuRateLimiter


def main() -> None:
    limiter = TpuRateLimiter(capacity=1 << 16, keymap="auto")
    now = time.time_ns()

    keys = [f"tenant:{i % 64}/user:{i}" for i in range(4096)]
    result = limiter.rate_limit_batch(
        keys, max_burst=10, count_per_period=100, period=60,
        quantity=1, now_ns=now,
    )
    print(f"batch 1: {int(result.allowed.sum())}/{len(keys)} allowed")

    # Hammer one key within a single batch: exact sequential semantics.
    hot = ["hot-key"] * 64
    result = limiter.rate_limit_batch(
        hot, max_burst=10, count_per_period=100, period=3600,
        quantity=1, now_ns=now,
    )
    print(
        f"hot key: {int(result.allowed.sum())}/64 allowed "
        f"(burst 10 → first 10: {bool(result.allowed[:10].all())})"
    )

    # Expiry sweep frees slots whose TTL lapsed.
    freed = limiter.sweep(now + 7200 * 10**9)
    print(f"sweep freed {freed} slots, {len(limiter)} live")

    # Per-key heterogeneous parameters in one batch.
    n = 1024
    rng = np.random.default_rng(0)
    result = limiter.rate_limit_batch(
        [f"k{i}" for i in range(n)],
        max_burst=rng.integers(1, 20, n),
        count_per_period=rng.integers(1, 1000, n),
        period=rng.integers(1, 3600, n),
        quantity=1,
        now_ns=now,
    )
    print(f"heterogeneous batch: {int(result.allowed.sum())}/{n} allowed")


if __name__ == "__main__":
    main()
