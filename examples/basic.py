"""Embedded library use — the reference's examples/basic.rs equivalent."""

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import time

import throttlecrab_tpu as tc


def main() -> None:
    limiter = tc.RateLimiter(tc.AdaptiveStore())
    now = time.time_ns()
    for i in range(7):
        allowed, result = limiter.rate_limit(
            "api:user:42",
            max_burst=5,
            count_per_period=100,
            period=60,
            quantity=1,
            now_ns=now + i * 1_000,
        )
        print(
            f"request {i}: allowed={allowed} remaining={result.remaining} "
            f"retry_after={result.retry_after_ns / 1e9:.3f}s"
        )


if __name__ == "__main__":
    main()
