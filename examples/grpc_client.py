"""Call a running throttlecrab-tpu server over gRPC — the client-side
example for the proto transport (reference:
throttlecrab-server/examples/grpc_client.rs:1-51).

Start a server first:
    python -m throttlecrab_tpu.server --grpc --grpc-port 9070

Then:
    python examples/grpc_client.py [--target 127.0.0.1:9070]

Needs grpcio (`pip install throttlecrab-tpu[grpc]`).  The method is
called through its full name, so no stub generation is required — the
request/response classes come from the checked-in *_pb2 module.
"""

from __future__ import annotations

import argparse
import os.path as _p
import sys as _s

_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import grpc

from throttlecrab_tpu.server.proto import throttlecrab_pb2 as pb


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", default="127.0.0.1:9070")
    args = ap.parse_args()

    channel = grpc.insecure_channel(args.target)
    throttle = channel.unary_unary(
        "/throttlecrab.RateLimiter/Throttle",
        request_serializer=pb.ThrottleRequest.SerializeToString,
        response_deserializer=pb.ThrottleResponse.FromString,
    )

    print("Burst 5, then denial:")
    for i in range(7):
        resp = throttle(
            pb.ThrottleRequest(
                key="grpc:user:99",
                max_burst=5,
                count_per_period=100,
                period=60,
                quantity=1,
            ),
            timeout=30,
        )
        verdict = "allowed" if resp.allowed else (
            f"DENIED (retry after {resp.retry_after}s)"
        )
        print(
            f"  request {i + 1}: {verdict}  "
            f"limit={resp.limit} remaining={resp.remaining}"
        )

    channel.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
