"""Mesh-sharded engine: key-shard data parallelism over all devices.

Run CPU-hermetic with:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/sharded_mesh.py --cpu
"""

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import jax

if "--cpu" in _s.argv:
    # In-process pin: the JAX_PLATFORMS env var alone is not honored
    # once an accelerator PJRT plugin registered via sitecustomize, and
    # a first device touch on a wedged serving tunnel hangs forever.
    jax.config.update("jax_platforms", "cpu")

import time

from throttlecrab_tpu.parallel import ShardedTpuRateLimiter
from throttlecrab_tpu.parallel.sharded import make_mesh


def main() -> None:
    mesh = make_mesh()  # every visible device
    print(f"mesh: {mesh.shape}")
    limiter = ShardedTpuRateLimiter(capacity_per_shard=1 << 14, mesh=mesh)
    now = time.time_ns()

    keys = [f"user:{i}" for i in range(8192)]
    result = limiter.rate_limit_batch(
        keys, max_burst=10, count_per_period=100, period=60,
        quantity=1, now_ns=now,
    )
    print(f"{int(result.allowed.sum())}/{len(keys)} allowed")
    # psum-reduced global counters (one collective over the mesh):
    print(f"global allowed={limiter.total_allowed} "
          f"denied={limiter.total_denied}")


if __name__ == "__main__":
    main()
