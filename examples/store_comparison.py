"""Cleanup-policy comparison on one workload — examples/store_comparison.rs.

The reference compares PeriodicStore / ProbabilisticStore / AdaptiveStore
throughput; here the three are *cleanup policies* over the same device
table (tpu/cleanup.py preserves each one's trigger rules verbatim), so the
comparison shows policy overhead and sweep cadence rather than separate
store implementations.

Run: python examples/store_comparison.py [--cpu]
"""

from __future__ import annotations

import os.path as _p, sys as _s
_s.path.insert(0, _p.dirname(_p.dirname(_p.abspath(__file__))))

import sys
import time

import numpy as np

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

from throttlecrab_tpu.tpu.cleanup import (
    AdaptivePolicy,
    PeriodicPolicy,
    ProbabilisticPolicy,
)
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_753_000_000 * NS
BATCH = 1024
BATCHES = 48
N_KEYS = 20_000


def run(name: str, policy) -> None:
    rng = np.random.default_rng(7)
    limiter = TpuRateLimiter(capacity=1 << 15)
    ids = rng.integers(0, N_KEYS, BATCH * BATCHES)
    keys = [f"key_{int(i)}" for i in ids]
    limiter.rate_limit_batch(keys[:BATCH], 100, 1000, 60, 1, T0)  # warm

    sweeps = 0
    freed_total = 0
    start = time.perf_counter()
    for b in range(BATCHES):
        now = T0 + b * 30 * NS  # 30 s per batch: TTLs lapse mid-run
        limiter.rate_limit_batch(
            keys[b * BATCH : (b + 1) * BATCH], 100, 1000, 60, 1, now,
            wire=True,
        )
        policy.record_ops(BATCH)
        if policy.should_clean(now, len(limiter), limiter.total_capacity):
            freed = limiter.sweep(now)
            policy.after_sweep(now, freed, len(limiter))
            sweeps += 1
            freed_total += freed
    dt = time.perf_counter() - start
    print(
        f"{name:>14}: {BATCH * BATCHES / dt:>12,.0f} decisions/s, "
        f"{sweeps} sweeps, {freed_total} slots reclaimed, "
        f"{len(limiter)} live"
    )


def main() -> None:
    run("periodic", PeriodicPolicy(interval_ns=60 * NS))
    run("probabilistic", ProbabilisticPolicy(probability=10))
    run("adaptive", AdaptivePolicy())


if __name__ == "__main__":
    main()
