// Host-side key→slot table for the TPU rate limiter.
//
// The reference's native hot path is its Rust HashMap keyed by string
// (throttlecrab/src/core/store/periodic.rs:39-47); in the TPU design the
// device owns the GCRA state and the host's per-request work shrinks to
// resolving string keys to dense slot indices.  At the 10M+ req/s target
// that resolution must not become the new bottleneck (SURVEY.md §7.4 hard
// part 2), hence this C++ open-addressing table with a batch API: one FFI
// call resolves a whole batch and emits the duplicate-segment structure
// (occurrence rank + last-occurrence flag) the device kernel needs — the
// Python fallback (throttlecrab_tpu/tpu/keymap.py) does the same with dicts.
//
// Design:
//   - open addressing, power-of-two bucket count, linear probing;
//   - FNV-1a 64-bit hashing;
//   - keys interned in an append-only arena (offset, len per entry);
//   - slot free-list for sweep recycling;
//   - per-batch segment tracking via a batch-stamp on each entry: no
//     per-call allocation, O(1) per request.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr uint64_t FNV_OFFSET = 1469598103934665603ULL;
constexpr uint64_t FNV_PRIME = 1099511628211ULL;

inline uint64_t fnv1a(const char* data, int64_t len) {
    uint64_t h = FNV_OFFSET;
    for (int64_t i = 0; i < len; i++) {
        h ^= static_cast<unsigned char>(data[i]);
        h *= FNV_PRIME;
    }
    return h;
}

struct Entry {
    uint64_t hash = 0;
    int64_t key_off = -1;   // -1: bucket empty
    int32_t key_len = 0;
    int32_t slot = -1;
    // Per-batch segment tracking.
    uint64_t batch_stamp = 0;
    int32_t batch_count = 0;
    int32_t batch_last_pos = -1;
};

struct KeyMap {
    std::vector<Entry> buckets;       // size is a power of two
    uint64_t mask = 0;
    std::vector<char> arena;          // interned key bytes
    std::vector<int32_t> free_slots;  // stack, low indices on top
    std::vector<int64_t> slot_bucket; // slot -> bucket index (-1 free)
    int64_t size = 0;                 // live keys
    int64_t capacity = 0;             // max slots
    uint64_t batch_stamp = 0;

    explicit KeyMap(int64_t cap) { init(cap); }

    void init(int64_t cap) {
        capacity = cap;
        uint64_t nbuckets = 16;
        while (nbuckets < static_cast<uint64_t>(cap) * 2) nbuckets <<= 1;
        buckets.assign(nbuckets, Entry{});
        mask = nbuckets - 1;
        free_slots.resize(cap);
        for (int64_t i = 0; i < cap; i++)
            free_slots[i] = static_cast<int32_t>(cap - 1 - i);
        slot_bucket.assign(cap, -1);
        arena.reserve(cap * 16);
    }

    void rehash(uint64_t nbuckets) {
        std::vector<Entry> old = std::move(buckets);
        buckets.assign(nbuckets, Entry{});
        mask = nbuckets - 1;
        for (const Entry& e : old) {
            if (e.key_off < 0) continue;
            uint64_t b = e.hash & mask;
            while (buckets[b].key_off >= 0) b = (b + 1) & mask;
            buckets[b] = e;
            slot_bucket[e.slot] = static_cast<int64_t>(b);
        }
    }

    void grow_slots(int64_t new_cap) {
        if (new_cap <= capacity) return;
        free_slots.reserve(new_cap);
        for (int64_t i = new_cap - 1; i >= capacity; i--)
            free_slots.push_back(static_cast<int32_t>(i));
        slot_bucket.resize(new_cap, -1);
        capacity = new_cap;
        if (static_cast<uint64_t>(new_cap) * 2 > buckets.size())
            rehash(buckets.size() * 2 >= static_cast<uint64_t>(new_cap) * 2
                       ? buckets.size()
                       : [&] {
                             uint64_t n = buckets.size();
                             while (n < static_cast<uint64_t>(new_cap) * 2) n <<= 1;
                             return n;
                         }());
    }
};

}  // namespace

extern "C" {

void* tk_create(int64_t capacity) { return new KeyMap(capacity); }

void tk_destroy(void* h) { delete static_cast<KeyMap*>(h); }

int64_t tk_len(void* h) { return static_cast<KeyMap*>(h)->size; }

int64_t tk_capacity(void* h) { return static_cast<KeyMap*>(h)->capacity; }

void tk_grow(void* h, int64_t new_capacity) {
    static_cast<KeyMap*>(h)->grow_slots(new_capacity);
}

// Resolve a batch of keys (concatenated bytes + offsets[n+1]) to slots,
// allocating on miss.  valid[i] == 0 skips a request (slot -1).  Emits the
// kernel's segment structure: rank (occurrence number within this batch) and
// is_last (final occurrence within this batch).  Returns the number of
// requests that could not be allocated because the table is full (their
// slots are -1; caller grows and retries just those, passing them as the
// only valid ones).
int64_t tk_lookup_insert_batch(
    void* h, const char* keys, const int64_t* offsets, int64_t n,
    const uint8_t* valid, int32_t* out_slots, int32_t* out_rank,
    uint8_t* out_is_last) {
    KeyMap* m = static_cast<KeyMap*>(h);
    m->batch_stamp++;
    const uint64_t stamp = m->batch_stamp;
    int64_t full = 0;
    for (int64_t i = 0; i < n; i++) {
        out_rank[i] = 0;
        out_is_last[i] = 1;
        if (!valid[i]) {
            out_slots[i] = -1;
            continue;
        }
        const char* key = keys + offsets[i];
        const int64_t len = offsets[i + 1] - offsets[i];
        const uint64_t hash = fnv1a(key, len);
        uint64_t b = hash & m->mask;
        Entry* e;
        for (;;) {
            e = &m->buckets[b];
            if (e->key_off < 0) break;  // miss
            if (e->hash == hash && e->key_len == len &&
                memcmp(m->arena.data() + e->key_off, key, len) == 0)
                break;  // hit
            b = (b + 1) & m->mask;
        }
        if (e->key_off < 0) {
            if (m->free_slots.empty()) {
                out_slots[i] = -1;
                full++;
                continue;
            }
            const int32_t slot = m->free_slots.back();
            m->free_slots.pop_back();
            e->hash = hash;
            e->key_off = static_cast<int64_t>(m->arena.size());
            e->key_len = static_cast<int32_t>(len);
            e->slot = slot;
            m->arena.insert(m->arena.end(), key, key + len);
            m->slot_bucket[slot] = static_cast<int64_t>(b);
            m->size++;
        }
        out_slots[i] = e->slot;
        if (e->batch_stamp == stamp) {
            out_rank[i] = ++e->batch_count - 1;
            out_is_last[e->batch_last_pos] = 0;
            e->batch_last_pos = static_cast<int32_t>(i);
        } else {
            e->batch_stamp = stamp;
            e->batch_count = 1;
            e->batch_last_pos = static_cast<int32_t>(i);
        }
    }
    return full;
}

// Snapshot export: first call tk_export_sizes to size the buffers, then
// tk_export fills slot ids, key offsets (n+1 entries) and key bytes for
// every live entry, in unspecified order.
void tk_export_sizes(void* h, int64_t* n_out, int64_t* bytes_out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    int64_t bytes = 0;
    for (const Entry& e : m->buckets)
        if (e.key_off >= 0) bytes += e.key_len;
    *n_out = m->size;
    *bytes_out = bytes;
}

void tk_export(void* h, int32_t* slots_out, int64_t* offsets_out,
               char* keys_out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    int64_t i = 0;
    int64_t off = 0;
    for (const Entry& e : m->buckets) {
        if (e.key_off < 0) continue;
        slots_out[i] = e.slot;
        offsets_out[i] = off;
        memcpy(keys_out + off, m->arena.data() + e.key_off, e.key_len);
        off += e.key_len;
        i++;
    }
    offsets_out[i] = off;
}

// Free the given slots (from a sweep's expired mask).  Tombstone-free
// removal for linear probing: re-place any displaced cluster members.
int64_t tk_free_slots(void* h, const int32_t* slots, int64_t n) {
    KeyMap* m = static_cast<KeyMap*>(h);
    int64_t freed = 0;
    for (int64_t i = 0; i < n; i++) {
        const int32_t slot = slots[i];
        if (slot < 0 || slot >= m->capacity) continue;
        int64_t b = m->slot_bucket[slot];
        if (b < 0) continue;  // not allocated
        // Backward-shift deletion keeps probe chains intact.
        uint64_t hole = static_cast<uint64_t>(b);
        m->buckets[hole] = Entry{};
        uint64_t j = (hole + 1) & m->mask;
        while (m->buckets[j].key_off >= 0) {
            const uint64_t home = m->buckets[j].hash & m->mask;
            // Can entry at j move into the hole without breaking its probe
            // sequence?  (standard backward-shift condition)
            const bool movable =
                ((j - home) & m->mask) >= ((j - hole) & m->mask);
            if (movable) {
                m->buckets[hole] = m->buckets[j];
                m->slot_bucket[m->buckets[hole].slot] =
                    static_cast<int64_t>(hole);
                m->buckets[j] = Entry{};
                hole = j;
            }
            j = (j + 1) & m->mask;
        }
        m->slot_bucket[slot] = -1;
        m->free_slots.push_back(slot);
        m->size--;
        freed++;
    }
    return freed;
}

}  // extern "C"
