// Host-side key→slot table for the TPU rate limiter.
//
// The reference's native hot path is its Rust HashMap keyed by string
// (throttlecrab/src/core/store/periodic.rs:39-47); in the TPU design the
// device owns the GCRA state and the host's per-request work shrinks to
// resolving string keys to dense slot indices.  At the 10M+ req/s target
// that resolution must not become the new bottleneck (SURVEY.md §7.4 hard
// part 2), hence this C++ open-addressing table with a batch API: one FFI
// call resolves a whole batch and emits the duplicate-segment structure
// (occurrence rank + last-occurrence flag) the device kernel needs — the
// Python fallback (throttlecrab_tpu/tpu/keymap.py) does the same with dicts.
//
// Design:
//   - open addressing, power-of-two bucket count, linear probing;
//   - FNV-1a 64-bit hashing;
//   - keys interned in an append-only arena (offset, len per entry);
//   - slot free-list for sweep recycling;
//   - per-batch segment tracking via a batch-stamp on each entry: no
//     per-call allocation, O(1) per request.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr uint64_t FNV_OFFSET = 1469598103934665603ULL;
constexpr uint64_t FNV_PRIME = 1099511628211ULL;

inline uint64_t fnv1a(const char* data, int64_t len) {
    uint64_t h = FNV_OFFSET;
    for (int64_t i = 0; i < len; i++) {
        h ^= static_cast<unsigned char>(data[i]);
        h *= FNV_PRIME;
    }
    return h;
}

struct Entry {
    uint64_t hash = 0;
    int64_t key_off = -1;   // -1: bucket empty
    int32_t key_len = 0;
    int32_t slot = -1;
};

struct KeyMap {
    std::vector<Entry> buckets;       // size is a power of two
    uint64_t mask = 0;
    std::vector<char> arena;          // interned key bytes
    std::vector<int32_t> free_slots;  // stack, low indices on top
    std::vector<int64_t> slot_bucket; // slot -> bucket index (-1 free)
    int64_t size = 0;                 // live keys
    int64_t capacity = 0;             // max slots
    uint64_t batch_stamp = 0;
    // id→key registry for tk_assemble: key bytes appended in intern order.
    std::vector<char> id_arena;
    std::vector<int64_t> id_off;      // n_ids + 1 offsets into id_arena
    // id→slot cache: after a key's first probe its slot is an O(1) array
    // read (the equivalent of the reference holding a HashMap entry
    // pointer).  slot_id is the reverse map so tk_free_slots can
    // invalidate exactly the freed keys' cache lines.
    std::vector<int32_t> id_slot;     // -1 = not cached
    std::vector<int32_t> slot_id;     // -1 = slot not owned by an id
    // Per-batch duplicate-segment tracking, indexed by slot (a slot
    // uniquely identifies a key within a batch, and slot indexing works
    // for both the probe path and the id-cache fast path).
    std::vector<uint64_t> slot_stamp;
    std::vector<int32_t> slot_count;
    std::vector<int32_t> slot_last_pos;
    std::vector<int32_t> slot_first_pos;

    explicit KeyMap(int64_t cap) { init(cap); }

    void init(int64_t cap) {
        id_off.assign(1, 0);
        capacity = cap;
        uint64_t nbuckets = 16;
        while (nbuckets < static_cast<uint64_t>(cap) * 2) nbuckets <<= 1;
        buckets.assign(nbuckets, Entry{});
        mask = nbuckets - 1;
        free_slots.resize(cap);
        for (int64_t i = 0; i < cap; i++)
            free_slots[i] = static_cast<int32_t>(cap - 1 - i);
        slot_bucket.assign(cap, -1);
        slot_id.assign(cap, -1);
        slot_stamp.assign(cap, 0);
        slot_count.assign(cap, 0);
        slot_last_pos.assign(cap, -1);
        slot_first_pos.assign(cap, -1);
        arena.reserve(cap * 16);
    }

    // Shared probe path for resolve / assemble / prepare: find the key's
    // entry, inserting on miss.  Returns nullptr with *full=true when the
    // slot table is exhausted.  Any change to probing or insertion
    // invariants happens HERE, once.
    Entry* find_or_insert(const char* key, int64_t len, bool* full) {
        *full = false;
        const uint64_t hash = fnv1a(key, len);
        uint64_t b = hash & mask;
        Entry* e;
        for (;;) {
            e = &buckets[b];
            if (e->key_off < 0) break;  // miss
            if (e->hash == hash && e->key_len == len &&
                memcmp(arena.data() + e->key_off, key, len) == 0)
                break;  // hit
            b = (b + 1) & mask;
        }
        if (e->key_off < 0) {
            if (free_slots.empty()) {
                *full = true;
                return nullptr;
            }
            const int32_t slot = free_slots.back();
            free_slots.pop_back();
            e->hash = hash;
            e->key_off = static_cast<int64_t>(arena.size());
            e->key_len = static_cast<int32_t>(len);
            e->slot = slot;
            arena.insert(arena.end(), key, key + len);
            slot_bucket[slot] = static_cast<int64_t>(b);
            size++;
        }
        return e;
    }

    void rehash(uint64_t nbuckets) {
        std::vector<Entry> old = std::move(buckets);
        buckets.assign(nbuckets, Entry{});
        mask = nbuckets - 1;
        for (const Entry& e : old) {
            if (e.key_off < 0) continue;
            uint64_t b = e.hash & mask;
            while (buckets[b].key_off >= 0) b = (b + 1) & mask;
            buckets[b] = e;
            slot_bucket[e.slot] = static_cast<int64_t>(b);
        }
    }

    void grow_slots(int64_t new_cap) {
        if (new_cap <= capacity) return;
        free_slots.reserve(new_cap);
        for (int64_t i = new_cap - 1; i >= capacity; i--)
            free_slots.push_back(static_cast<int32_t>(i));
        slot_bucket.resize(new_cap, -1);
        slot_id.resize(new_cap, -1);
        slot_stamp.resize(new_cap, 0);
        slot_count.resize(new_cap, 0);
        slot_last_pos.resize(new_cap, -1);
        slot_first_pos.resize(new_cap, -1);
        capacity = new_cap;
        // Keep nbuckets >= 2 * capacity (load factor <= 0.5): the probe
        // loops rely on an empty bucket always existing — at load factor
        // 1.0 a miss probe never terminates.
        if (static_cast<uint64_t>(new_cap) * 2 > buckets.size()) {
            uint64_t n = buckets.size();
            while (n < static_cast<uint64_t>(new_cap) * 2) n <<= 1;
            rehash(n);
        }
    }
};

}  // namespace

extern "C" {

void* tk_create(int64_t capacity) { return new KeyMap(capacity); }

void tk_destroy(void* h) { delete static_cast<KeyMap*>(h); }

int64_t tk_len(void* h) { return static_cast<KeyMap*>(h)->size; }

int64_t tk_capacity(void* h) { return static_cast<KeyMap*>(h)->capacity; }

void tk_grow(void* h, int64_t new_capacity) {
    static_cast<KeyMap*>(h)->grow_slots(new_capacity);
}

// Resolve a batch of keys (concatenated bytes + offsets[n+1]) to slots,
// allocating on miss.  valid[i] == 0 skips a request (slot -1).  Emits the
// kernel's segment structure: rank (occurrence number within this batch) and
// is_last (final occurrence within this batch).  Returns the number of
// requests that could not be allocated because the table is full (their
// slots are -1; caller grows and retries just those, passing them as the
// only valid ones).
int64_t tk_lookup_insert_batch(
    void* h, const char* keys, const int64_t* offsets, int64_t n,
    const uint8_t* valid, int32_t* out_slots, int32_t* out_rank,
    uint8_t* out_is_last) {
    KeyMap* m = static_cast<KeyMap*>(h);
    m->batch_stamp++;
    const uint64_t stamp = m->batch_stamp;
    int64_t full = 0;
    for (int64_t i = 0; i < n; i++) {
        out_rank[i] = 0;
        out_is_last[i] = 1;
        if (!valid[i]) {
            out_slots[i] = -1;
            continue;
        }
        const char* key = keys + offsets[i];
        const int64_t len = offsets[i + 1] - offsets[i];
        bool is_full = false;
        Entry* e = m->find_or_insert(key, len, &is_full);
        if (is_full) {
            out_slots[i] = -1;
            full++;
            continue;
        }
        const int32_t slot = e->slot;
        out_slots[i] = slot;
        if (m->slot_stamp[slot] == stamp) {
            out_rank[i] = ++m->slot_count[slot] - 1;
            out_is_last[m->slot_last_pos[slot]] = 0;
            m->slot_last_pos[slot] = static_cast<int32_t>(i);
        } else {
            m->slot_stamp[slot] = stamp;
            m->slot_count[slot] = 1;
            m->slot_last_pos[slot] = static_cast<int32_t>(i);
        }
    }
    return full;
}

// ---------------------------------------------------------------------
// Id-based launch assembly: the round-4 host fast path.
//
// The Python list-comprehension batch assembly (`[key_src[i] for i in sel]`
// + per-sub-batch resolve) capped the host at ~1.7 M decisions/s.  Here the
// caller interns its key universe once (tk_intern_keys) and then builds an
// entire K×B launch buffer with ONE call (tk_assemble) straight from an id
// array: per request the interned key bytes are re-hashed through the table
// (the same per-request probe work the serving path pays — interning skips
// only the Python object traffic), slots are allocated on miss, the
// duplicate-segment structure is tracked per micro-batch of `batch`
// requests, and the kernel's packed i32[PACK_WIDTH] row is written in
// place (layout must match kernel.py PACK_WIDTH/pack_requests:
//   w0 slot | w1 rank | w2 flags(bit0 is_last, bit1 valid)
//   w3/4 emission lo/hi | w5/6 tolerance lo/hi | w7/8 quantity lo/hi).

constexpr int64_t PACK_W = 9;

// Resolve an interned id to its slot: O(1) via the id→slot cache after
// the first touch, else hash + probe (allocating on miss) and cache.
// Returns -1 when the slot table is full.  Shared by tk_assemble,
// tk_assemble_ids and tk_resolve_all so the caching rule cannot drift.
static int32_t resolve_interned(KeyMap* m, int64_t id) {
    int32_t slot = m->id_slot[id];
    if (slot >= 0) return slot;
    const char* key = m->id_arena.data() + m->id_off[id];
    const int64_t len = m->id_off[id + 1] - m->id_off[id];
    bool is_full = false;
    Entry* e = m->find_or_insert(key, len, &is_full);
    if (is_full) return -1;
    slot = e->slot;
    // Cache only an unclaimed slot: two interned ids with identical key
    // bytes share a slot, and the reverse map can hold just one of them
    // — the other stays slow-path.
    if (m->slot_id[slot] < 0) {
        m->slot_id[slot] = static_cast<int32_t>(id);
        m->id_slot[id] = slot;
    }
    return slot;
}

// Register `n` keys; ids are assigned sequentially.  Returns the first id.
int64_t tk_intern_keys(void* h, const char* keys, const int64_t* offsets,
                       int64_t n) {
    KeyMap* m = static_cast<KeyMap*>(h);
    const int64_t first = static_cast<int64_t>(m->id_off.size()) - 1;
    for (int64_t i = 0; i < n; i++) {
        const int64_t len = offsets[i + 1] - offsets[i];
        m->id_arena.insert(m->id_arena.end(), keys + offsets[i],
                           keys + offsets[i] + len);
        m->id_off.push_back(static_cast<int64_t>(m->id_arena.size()));
        m->id_slot.push_back(-1);
    }
    return first;
}

// Build a launch buffer of `total` requests (micro-batches of `batch`) from
// interned key ids.  em/tol are per-id parameter tables; `quantity` is a
// uniform per-request quantity (the serving engine certifies uniformity
// before taking this path).  ids < 0 are padding (written invalid, not
// counted).  Returns the number of requests dropped — slot table full, or
// a non-negative id that was never interned (both written invalid) — so a
// forgotten intern() fails the caller's `n_full == 0` check instead of
// silently reporting undecided requests.
int64_t tk_assemble(void* h, const int32_t* ids, int64_t total, int64_t batch,
                    const int64_t* em_by_id, const int64_t* tol_by_id,
                    int64_t quantity, int32_t* out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    const int64_t n_ids = static_cast<int64_t>(m->id_off.size()) - 1;
    const int32_t qlo = static_cast<int32_t>(quantity & 0xFFFFFFFFll);
    const int32_t qhi = static_cast<int32_t>(quantity >> 32);
    int64_t full = 0;
    for (int64_t base = 0; base < total; base += batch) {
        m->batch_stamp++;
        const uint64_t stamp = m->batch_stamp;
        const int64_t end = base + batch < total ? base + batch : total;
        for (int64_t i = base; i < end; i++) {
            int32_t* w = out + i * PACK_W;
            const int64_t id = ids[i];
            if (id < 0 || id >= n_ids) {
                w[0] = -1;
                for (int j = 1; j < PACK_W; j++) w[j] = 0;
                if (id >= n_ids) full++;  // un-interned id: surface it
                continue;
            }
            const int32_t slot = resolve_interned(m, id);
            if (slot < 0) {
                w[0] = -1;
                for (int j = 1; j < PACK_W; j++) w[j] = 0;
                full++;
                continue;
            }
            w[0] = slot;
            w[2] = 3;  // is_last | valid
            if (m->slot_stamp[slot] == stamp) {
                w[1] = ++m->slot_count[slot] - 1;
                out[static_cast<int64_t>(m->slot_last_pos[slot]) * PACK_W +
                    2] &= ~1;
                m->slot_last_pos[slot] = static_cast<int32_t>(i);
            } else {
                w[1] = 0;
                m->slot_stamp[slot] = stamp;
                m->slot_count[slot] = 1;
                m->slot_last_pos[slot] = static_cast<int32_t>(i);
            }
            const int64_t em = em_by_id[id];
            const int64_t tol = tol_by_id[id];
            w[3] = static_cast<int32_t>(em & 0xFFFFFFFFll);
            w[4] = static_cast<int32_t>(em >> 32);
            w[5] = static_cast<int32_t>(tol & 0xFFFFFFFFll);
            w[6] = static_cast<int32_t>(tol >> 32);
            w[7] = qlo;
            w[8] = qhi;
        }
    }
    return full;
}

// ---------------------------------------------------------------------
// By-id launch assembly: the minimum-bytes request path.
//
// The serving tunnel moves ~10-50 MB/s TOTAL (both directions, serialized
// — scripts/probe_d2h.py / probe_duplex.py), so the 36 B/request packed
// row is the launch-dominating payload.  When the key universe is
// interned and its parameter rows are resident on the DEVICE
// (tpu/table.py upload_id_rows), a request needs only its id plus the
// duplicate-segment structure: ONE i64 word
//   low 32 bits: id | high 32: rank(14) | is_last<<14 | valid<<15
// — 8 B/request, 4.5x less than the packed row.  The device gathers
// (slot, emission, tolerance) from the resident rows by id.
//
// Contract (the bench/serving caller certifies): every id interned, ids
// canonical enough that ids sharing a SLOT share parameters (segments
// are tracked per slot, exactly like tk_assemble, so duplicate key
// BYTES under different ids still serialize correctly).

// Resolve every interned id to a slot (allocating on miss) and fill the
// caller's id→slot array — the host half of the device id-row upload.
// Returns the number of ids that could not get a slot (table full);
// their slots_out entry is -1.
int64_t tk_resolve_all(void* h, int32_t* slots_out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    const int64_t n_ids = static_cast<int64_t>(m->id_off.size()) - 1;
    int64_t failed = 0;
    for (int64_t id = 0; id < n_ids; id++) {
        const int32_t slot = resolve_interned(m, id);
        slots_out[id] = slot;
        if (slot < 0) failed++;
    }
    return failed;
}

// Build the i64 request words for a launch of `total` requests
// (micro-batches of `batch`) straight from an id array.  ids < 0 are
// padding (valid=0).  Returns the number of requests dropped (id never
// interned / table full — written invalid so the caller's n_bad check
// catches a forgotten intern or resolve).
int64_t tk_assemble_ids(void* h, const int32_t* ids, int64_t total,
                        int64_t batch, int64_t* out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    const int64_t n_ids = static_cast<int64_t>(m->id_off.size()) - 1;
    int64_t bad = 0;
    for (int64_t base = 0; base < total; base += batch) {
        m->batch_stamp++;
        const uint64_t stamp = m->batch_stamp;
        const int64_t end = base + batch < total ? base + batch : total;
        for (int64_t i = base; i < end; i++) {
            const int64_t id = ids[i];
            if (id < 0 || id >= n_ids) {
                out[i] = 0;  // valid=0
                if (id >= n_ids) bad++;
                continue;
            }
            const int32_t slot = resolve_interned(m, id);
            if (slot < 0) {
                out[i] = 0;
                bad++;
                continue;
            }
            int64_t meta;
            if (m->slot_stamp[slot] == stamp) {
                const int32_t rank = m->slot_count[slot]++;
                // Clear the previous occurrence's is_last bit.
                out[m->slot_last_pos[slot]] &=
                    ~(static_cast<int64_t>(1) << 46);
                m->slot_last_pos[slot] = static_cast<int32_t>(i);
                meta = rank | (1 << 14) | (1 << 15);
            } else {
                m->slot_stamp[slot] = stamp;
                m->slot_count[slot] = 1;
                m->slot_last_pos[slot] = static_cast<int32_t>(i);
                meta = (1 << 14) | (1 << 15);
            }
            out[i] = (meta << 32) | static_cast<uint32_t>(id);
        }
    }
    return bad;
}

// One request's wire completion from its `cur*2+allowed` word: the exact
// arithmetic shared by tk_finish (packed rows) and tk_finish_ids (by-id
// tables) so the two paths cannot drift.  Under the fits_cur_wire +
// with_degen=False certificate (kernel.py) no intermediate leaves i64.
static inline void finish_one(int64_t em, int64_t tol, int64_t qty,
                              int64_t c2, int64_t now, int32_t* o) {
    constexpr int64_t I32MAX = 2147483647ll;
    constexpr int64_t NSEC = 1000000000ll;
    const int64_t allowed = c2 & 1;
    const int64_t cur = c2 >> 1;  // arithmetic: exact for negatives
    const int64_t room = now + tol - cur;
    int64_t remaining = em > 0 ? room / em : 0;
    if (remaining < 0) remaining = 0;
    int64_t reset = cur - now + tol;
    if (reset < 0) reset = 0;
    int64_t retry = allowed ? 0 : cur + em * qty - tol - now;
    if (retry < 0) retry = 0;
    o[0] = static_cast<int32_t>(allowed);
    o[1] = static_cast<int32_t>(remaining < I32MAX ? remaining : I32MAX);
    const int64_t reset_s = reset / NSEC;
    o[2] = static_cast<int32_t>(reset_s < I32MAX ? reset_s : I32MAX);
    const int64_t retry_s = retry / NSEC;
    o[3] = static_cast<int32_t>(retry_s < I32MAX ? retry_s : I32MAX);
}

// tk_finish for the raw-ids path (gcra_scan_ids): the request stream is
// bare i32 ids (negative = padding), parameters from the host tables.
void tk_finish_raw(const int32_t* ids, const int64_t* em_by_id,
                   const int64_t* tol_by_id, int64_t quantity,
                   const int64_t* cur2, int64_t n, int64_t now,
                   int32_t* out) {
    for (int64_t i = 0; i < n; i++) {
        const int32_t id = ids[i];
        const bool valid = id >= 0;
        const int64_t em = valid ? em_by_id[id] : 0;
        const int64_t tol = valid ? tol_by_id[id] : 0;
        finish_one(em, tol, quantity, cur2[i], now, out + i * 4);
    }
}

// tk_finish for the by-id path: emission/tolerance come from the host
// parameter tables indexed by the id in each request word; quantity is
// the launch-uniform scalar.
void tk_finish_ids(const int64_t* words, const int64_t* em_by_id,
                   const int64_t* tol_by_id, int64_t quantity,
                   const int64_t* cur2, int64_t n, int64_t now,
                   int32_t* out) {
    for (int64_t i = 0; i < n; i++) {
        const int64_t word = words[i];
        const int64_t id = static_cast<uint32_t>(word);
        const bool valid = (word >> 47) & 1;
        const int64_t em = valid ? em_by_id[id] : 0;
        const int64_t tol = valid ? tol_by_id[id] : 0;
        finish_one(em, tol, quantity, cur2[i], now, out + i * 4);
    }
}

// Host-side completion of the kernel's compact="cur" device output:
// reconstruct the exact 4-plane wire values (allowed, remaining,
// reset_after_secs, retry_after_secs — i32, saturated exactly like the
// kernel's compact branch) from ONE i64 `cur*2 + allowed` per request,
// reading emission/tolerance/quantity back out of the packed request
// rows the caller already holds.  Under the fits_cur_wire +
// with_degen=False certificate (kernel.py) no intermediate can leave
// i64, so plain arithmetic reproduces the device's saturating ops
// bit-for-bit.  Moving these two i64 divisions off the device halves
// the launch's device→host bytes AND removes emulated 64-bit VPU work.
void tk_finish(const int32_t* packed, const int64_t* cur2, int64_t n,
               int64_t now, int32_t* out) {
    for (int64_t i = 0; i < n; i++) {
        const int32_t* w = packed + i * PACK_W;
        const int64_t em =
            (static_cast<int64_t>(w[4]) << 32) |
            static_cast<uint32_t>(w[3]);
        const int64_t tol =
            (static_cast<int64_t>(w[6]) << 32) |
            static_cast<uint32_t>(w[5]);
        const int64_t qty =
            (static_cast<int64_t>(w[8]) << 32) |
            static_cast<uint32_t>(w[7]);
        finish_one(em, tol, qty, cur2[i], now, out + i * 4);
    }
}

// ---------------------------------------------------------------------
// Wire-batch preparation: the fully-native serving host path.
//
// One call takes a micro-batch exactly as the C++ wire layer hands it
// over (concatenated key bytes + offsets + i64 (burst, count, period,
// quantity) per request) and produces the kernel's packed launch rows:
// per request it validates (reference error taxonomy), derives the GCRA
// parameters with the exact f64 pipeline (rate/mod.rs:164-176 semantics:
// f64 multiply/divide, truncating cast, wrapping tolerance product —
// bit-identical to limiter.derive_params), resolves the slot, emits the
// duplicate-segment structure, and writes the packed row.  Python's
// per-batch work drops to padding + the device launch.
//
// Returns a flag bitmask; a nonzero TK_PREP_CONFLICT or TK_PREP_FULL
// tells the caller to fall back to the exact Python path (param changes
// mid-batch need the multi-round sub-protocol; full tables need growth).

constexpr int64_t TK_PREP_DEGEN = 1;     // needs the exact kernel path
constexpr int64_t TK_PREP_CONFLICT = 2;  // same key, different params
constexpr int64_t TK_PREP_FULL = 4;      // slot table full
constexpr int64_t TK_PREP_BIGTOL = 8;    // tol >= 2^61: no "cur" wire mode

constexpr uint8_t STATUS_OK = 0;
constexpr uint8_t STATUS_NEGATIVE_QUANTITY = 1;
constexpr uint8_t STATUS_INVALID_PARAMS = 2;

// agg (i64[4], may be null): aggregate bounds over STATUS_OK lanes for
// the caller's O(1) compact="w32" certificate (kernel.fits_w32_wire's
// native twin): [max_tol, min_tol, max_inc (saturated), max of the
// per-lane remaining bound (tol + max(em, tol)) / em].  Lanes the
// validator rejects never reach the kernel, so they are excluded.
int64_t tk_prepare_batch(void* h, const char* keys, const int64_t* offsets,
                         int64_t n, const int64_t* params, int32_t* out,
                         uint8_t* status, int64_t* agg) {
    KeyMap* m = static_cast<KeyMap*>(h);
    m->batch_stamp++;
    const uint64_t stamp = m->batch_stamp;
    int64_t flags = 0;
    int64_t max_tol = 0, min_tol = INT64_MAX, max_inc = 0, max_remb = 0;
    // Per-slot first-occurrence params for conflict detection, reset via
    // the same stamp the segment tracking uses.
    for (int64_t i = 0; i < n; i++) {
        int32_t* w = out + i * PACK_W;
        const int64_t burst = params[i * 4 + 0];
        const int64_t count = params[i * 4 + 1];
        const int64_t period = params[i * 4 + 2];
        const int64_t qty = params[i * 4 + 3];

        uint8_t st = STATUS_OK;
        if (burst <= 0 || count <= 0 || period <= 0)
            st = STATUS_INVALID_PARAMS;
        if (qty < 0) st = STATUS_NEGATIVE_QUANTITY;
        status[i] = st;
        if (st != STATUS_OK) {
            w[0] = -1;
            for (int j = 1; j < PACK_W; j++) w[j] = 0;
            continue;
        }

        // Exact f64 derivation (matches limiter.derive_params): numpy and
        // C++ both follow IEEE-754 double semantics here.
        const double emission_f =
            static_cast<double>(period) * 1e9 / static_cast<double>(count);
        int64_t em;
        if (emission_f >= 9223372036854775808.0)  // 2^63
            em = INT64_MAX;
        else
            em = static_cast<int64_t>(emission_f);
        if (em < 0) em = 0;
        const uint64_t b32 =
            static_cast<uint64_t>(burst - 1) & 0xFFFFFFFFull;
        const int64_t tol = static_cast<int64_t>(
            static_cast<uint64_t>(em) * b32);  // wrapping, as reference

        if (em == 0 || tol <= 0 || qty == 0) flags |= TK_PREP_DEGEN;
        // Segment-arithmetic overflow certificate (must mirror
        // limiter.has_degenerate): inc * MAX_SEGMENT must stay below
        // 2^62 or the kernel's certified plain multiplies could wrap.
        if (static_cast<double>(em) * static_cast<double>(qty > 1 ? qty : 1)
                * 65536.0
            >= 4611686018427387904.0)  // 2^62
            flags |= TK_PREP_DEGEN;
        // fits_cur_wire half of the compact="cur" certificate (kernel.py):
        // tol >= 2^61 would overflow the cur*2+allowed wire word.  (The
        // now < 2^61 half is the caller's, since `now` arrives at launch
        // time.)
        if (tol >= (int64_t(1) << 61)) flags |= TK_PREP_BIGTOL;

        // w32-certificate aggregates (see header comment).
        if (tol > max_tol) max_tol = tol;
        if (tol < min_tol) min_tol = tol;
        {
            // Saturating em * qty (the bound only needs the clamp).
            const double inc_f =
                static_cast<double>(em) * static_cast<double>(qty);
            const int64_t inc = inc_f >= 9223372036854775807.0
                                    ? INT64_MAX
                                    : static_cast<int64_t>(inc_f);
            if (inc > max_inc) max_inc = inc;
            if (em > 0 && tol >= 0 && tol < (int64_t(1) << 61)) {
                // Saturating sum: em is only bounded by i64, so
                // tol + em can overflow (UB on signed i64) — the same
                // double-probe pattern as max_inc above.  (Such lanes
                // are also PREP_DEGEN via the big-inc certificate, but
                // the aggregate must stay well-defined regardless.)
                const int64_t big = em > tol ? em : tol;
                const int64_t room =
                    static_cast<double>(tol) + static_cast<double>(big)
                            >= 9223372036854775807.0
                        ? INT64_MAX
                        : tol + big;
                const int64_t remb = room / em;
                if (remb > max_remb) max_remb = remb;
            } else {
                max_remb = INT64_MAX;  // degen/bigtol lane: refuse w32
            }
        }

        const char* key = keys + offsets[i];
        const int64_t len = offsets[i + 1] - offsets[i];
        bool is_full = false;
        Entry* e = m->find_or_insert(key, len, &is_full);
        if (is_full) {
            w[0] = -1;
            for (int j = 1; j < PACK_W; j++) w[j] = 0;
            flags |= TK_PREP_FULL;
            continue;
        }
        const int32_t slot = e->slot;
        w[0] = slot;
        w[2] = 3;  // is_last | valid
        if (m->slot_stamp[slot] == stamp) {
            w[1] = ++m->slot_count[slot] - 1;
            out[static_cast<int64_t>(m->slot_last_pos[slot]) * PACK_W + 2] &=
                ~1;
            // Conflict: this occurrence's derived params must match the
            // first occurrence's packed row (the kernel requires uniform
            // params per slot per batch).
            const int64_t f =
                static_cast<int64_t>(m->slot_first_pos[slot]) * PACK_W;
            const int32_t em_lo = static_cast<int32_t>(em & 0xFFFFFFFFll);
            const int32_t em_hi = static_cast<int32_t>(em >> 32);
            const int32_t tol_lo = static_cast<int32_t>(tol & 0xFFFFFFFFll);
            const int32_t tol_hi = static_cast<int32_t>(tol >> 32);
            const int32_t q_lo = static_cast<int32_t>(qty & 0xFFFFFFFFll);
            const int32_t q_hi = static_cast<int32_t>(qty >> 32);
            if (out[f + 3] != em_lo || out[f + 4] != em_hi ||
                out[f + 5] != tol_lo || out[f + 6] != tol_hi ||
                out[f + 7] != q_lo || out[f + 8] != q_hi)
                flags |= TK_PREP_CONFLICT;
            m->slot_last_pos[slot] = static_cast<int32_t>(i);
        } else {
            w[1] = 0;
            m->slot_stamp[slot] = stamp;
            m->slot_count[slot] = 1;
            m->slot_last_pos[slot] = static_cast<int32_t>(i);
            m->slot_first_pos[slot] = static_cast<int32_t>(i);
        }
        w[3] = static_cast<int32_t>(em & 0xFFFFFFFFll);
        w[4] = static_cast<int32_t>(em >> 32);
        w[5] = static_cast<int32_t>(tol & 0xFFFFFFFFll);
        w[6] = static_cast<int32_t>(tol >> 32);
        w[7] = static_cast<int32_t>(qty & 0xFFFFFFFFll);
        w[8] = static_cast<int32_t>(qty >> 32);
    }
    if (agg) {
        agg[0] = max_tol;
        agg[1] = min_tol == INT64_MAX ? 0 : min_tol;
        agg[2] = max_inc;
        agg[3] = max_remb;
    }
    return flags;
}

// Snapshot export: first call tk_export_sizes to size the buffers, then
// tk_export fills slot ids, key offsets (n+1 entries) and key bytes for
// every live entry, in unspecified order.
void tk_export_sizes(void* h, int64_t* n_out, int64_t* bytes_out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    int64_t bytes = 0;
    for (const Entry& e : m->buckets)
        if (e.key_off >= 0) bytes += e.key_len;
    *n_out = m->size;
    *bytes_out = bytes;
}

void tk_export(void* h, int32_t* slots_out, int64_t* offsets_out,
               char* keys_out) {
    KeyMap* m = static_cast<KeyMap*>(h);
    int64_t i = 0;
    int64_t off = 0;
    for (const Entry& e : m->buckets) {
        if (e.key_off < 0) continue;
        slots_out[i] = e.slot;
        offsets_out[i] = off;
        memcpy(keys_out + off, m->arena.data() + e.key_off, e.key_len);
        off += e.key_len;
        i++;
    }
    offsets_out[i] = off;
}

// Free the given slots (from a sweep's expired mask).  Tombstone-free
// removal for linear probing: re-place any displaced cluster members.
int64_t tk_free_slots(void* h, const int32_t* slots, int64_t n) {
    KeyMap* m = static_cast<KeyMap*>(h);
    int64_t freed = 0;
    for (int64_t i = 0; i < n; i++) {
        const int32_t slot = slots[i];
        if (slot < 0 || slot >= m->capacity) continue;
        int64_t b = m->slot_bucket[slot];
        if (b < 0) continue;  // not allocated
        // Backward-shift deletion keeps probe chains intact.
        uint64_t hole = static_cast<uint64_t>(b);
        m->buckets[hole] = Entry{};
        uint64_t j = (hole + 1) & m->mask;
        while (m->buckets[j].key_off >= 0) {
            const uint64_t home = m->buckets[j].hash & m->mask;
            // Can entry at j move into the hole without breaking its probe
            // sequence?  (standard backward-shift condition)
            const bool movable =
                ((j - home) & m->mask) >= ((j - hole) & m->mask);
            if (movable) {
                m->buckets[hole] = m->buckets[j];
                m->slot_bucket[m->buckets[hole].slot] =
                    static_cast<int64_t>(hole);
                m->buckets[j] = Entry{};
                hole = j;
            }
            j = (j + 1) & m->mask;
        }
        m->slot_bucket[slot] = -1;
        if (m->slot_id[slot] >= 0) {
            m->id_slot[m->slot_id[slot]] = -1;
            m->slot_id[slot] = -1;
        }
        m->free_slots.push_back(slot);
        m->size--;
        freed++;
    }
    return freed;
}

}  // extern "C"
