// Native RESP wire front-end for the TPU rate limiter.
//
// The reference's transport hot path is tokio Rust (redis/mod.rs); here the
// wire layer is a C++ epoll loop so the Python process spends its cycles
// only on the batched device decide.  Division of labor:
//
//   IO thread (C++):   accept, read, RESP parse, PING/QUIT/parse errors
//                      answered inline; THROTTLE requests assembled into
//                      a lock-protected pending queue (key bytes + i64
//                      params + connection cookie).
//   driver (Python):   ws_next_batch() blocks until requests are pending
//                      (or timeout), copies them into numpy arrays, runs
//                      TpuRateLimiter.rate_limit_batch, then ws_respond()
//                      hands the 5-integer results back.
//   IO thread (C++):   serializes RESP arrays into per-connection output
//                      buffers and flushes via epoll writability.
//
// The C++ side enforces the reference's connection hardening: 64 KB read
// buffer cap and 5-minute idle timeout (redis/mod.rs:83-149).  Command
// semantics mirror redis/mod.rs:150-296 (case-insensitive, argument
// validation order, exact error strings).
//
// C ABI only (ctypes); no Python.h dependency.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t MAX_CONN_BUFFER = 64 * 1024;     // redis/mod.rs:83
constexpr int64_t IDLE_TIMEOUT_MS = 300 * 1000;   // redis/mod.rs:99
constexpr int64_t MAX_BULK = 512LL * 1024 * 1024; // resp.rs:8
constexpr int64_t MAX_ARRAY = 1024 * 1024;        // resp.rs:9

int64_t now_ms() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

struct PendingRequest {
    uint64_t conn_gen;   // connection generation cookie
    int fd;
    uint64_t slot_seq;   // position in the connection's response order
    std::string key;
    int64_t max_burst, count_per_period, period, quantity;
    // Absolute client deadline on the CLOCK_MONOTONIC ms clock
    // (0 = none).  Stamped at parse time; ws_next_batch converts it to
    // a remaining-budget column so the driver sheds expired rows
    // before device dispatch.
    int64_t deadline_ms = 0;
    bool keep_alive = true;  // HTTP only: close after responding if false
};

// One entry per request in a connection's response order.  Inline replies
// (PING, QUIT, errors, /health, 404) are born ready; THROTTLE slots fill
// when the driver responds.  The writer only ever flushes the ready
// prefix, so pipelined responses leave in exactly request order — the
// property RESP and HTTP/1.1 both require and the asyncio backends get
// for free from their sequential loops.
struct Slot {
    bool ready = false;
    bool close_after = false;
    std::string payload;
};

// Driver-side identity of an in-flight THROTTLE, FIFO-paired with the
// batch handed to ws_next_batch so ws_respond can route each result back
// to its connection slot.
struct Inflight {
    uint64_t conn_gen;
    int fd;
    uint64_t slot_seq;
    bool keep_alive;
};

// A completed response on its way from the driver thread to the IO
// thread, addressed by (gen, fd, slot_seq).
struct Response {
    uint64_t conn_gen;
    int fd;
    uint64_t slot_seq;
    bool close_after;
    std::string payload;
};

// Per-connection response-queue backpressure: a stalled driver must not
// let inline replies (PING floods) accumulate unboundedly behind an
// unready THROTTLE slot, so past these caps the connection stops reading
// until the queue drains below half.
constexpr size_t OUT_SLOT_CAP = 16384;
constexpr size_t OUT_BYTES_CAP = 1 << 20;

struct Conn {
    int fd = -1;
    uint64_t gen = 0;
    std::string rbuf;
    std::string wbuf;
    std::deque<Slot> slots;   // response order; front() has seq slot_base
    uint64_t slot_base = 0;   // seq of slots.front()
    size_t slots_bytes = 0;   // queued payload bytes across slots
    int64_t last_activity_ms = 0;
    bool closing = false;     // close once wbuf drains
    bool draining = false;    // close-after slot enqueued: stop parsing
    bool rd_closed = false;   // client half-closed; flush remaining slots
    bool out_paused = false;  // response queue over cap: stop reading
    bool want_write = false;
};

// Incremental RESP array-of-bulk-strings parser (the only client frames the
// reference accepts for commands; inline commands are not supported there
// either).  Returns: 1 = one command parsed, 0 = need more data,
// -1 = protocol error (err filled).
int parse_command(const std::string& buf, size_t& consumed,
                  std::vector<std::string>& out,
                  std::vector<uint8_t>& nulls, std::string& err) {
    out.clear();
    nulls.clear();
    size_t pos = 0;
    auto read_line = [&](std::string& line) -> int {
        size_t idx = buf.find("\r\n", pos);
        if (idx == std::string::npos) return 0;
        line.assign(buf, pos, idx - pos);
        pos = idx + 2;
        return 1;
    };
    auto parse_int = [](const std::string& s, int64_t& v) -> bool {
        if (s.empty()) return false;
        size_t i = (s[0] == '-' || s[0] == '+') ? 1 : 0;
        if (i == s.size()) return false;
        for (size_t j = i; j < s.size(); j++)
            if (s[j] < '0' || s[j] > '9') return false;
        errno = 0;
        v = strtoll(s.c_str(), nullptr, 10);
        return errno == 0;
    };

    if (buf.empty()) return 0;
    if (buf[0] != '*') {
        err = "ERR expected array of commands";
        return -1;
    }
    std::string line;
    if (!read_line(line)) return 0;
    int64_t count;
    if (!parse_int(line.substr(1), count) || count < -1 ||
        count > MAX_ARRAY) {
        err = "ERR Invalid array size";
        return -1;
    }
    if (count <= 0) {
        consumed = pos;
        return 1;  // empty command → dispatch will answer
    }
    for (int64_t i = 0; i < count; i++) {
        if (pos >= buf.size()) return 0;
        if (buf[pos] != '$') {
            err = "ERR invalid command format";
            return -1;
        }
        if (!read_line(line)) return 0;
        int64_t len;
        if (!parse_int(line.substr(1), len) || len < -1 || len > MAX_BULK) {
            err = "ERR Invalid bulk string length";
            return -1;
        }
        if (len == -1) {
            // Null bulk string: kept distinct from "" so dispatch can
            // reject it per-argument like the reference does.
            out.emplace_back();
            nulls.push_back(1);
            continue;
        }
        if (buf.size() < pos + static_cast<size_t>(len) + 2) return 0;
        out.emplace_back(buf, pos, len);
        nulls.push_back(0);
        pos += len + 2;
    }
    consumed = pos;
    return 1;
}

bool parse_i64_ascii(const std::string& s, int64_t& v) {
    if (s.empty()) return false;
    size_t i = (s[0] == '-' || s[0] == '+') ? 1 : 0;
    if (i == s.size()) return false;
    for (size_t j = i; j < s.size(); j++)
        if (s[j] < '0' || s[j] > '9') return false;
    errno = 0;
    v = strtoll(s.c_str(), nullptr, 10);
    return errno != ERANGE;
}

std::string upper(const std::string& s) {
    std::string o = s;
    for (char& c : o)
        if (c >= 'a' && c <= 'z') c -= 32;
    return o;
}

// Minimal JSON field extraction for the fixed /throttle schema
// (http.rs:61-73).  Scans for "name" outside strings; handles \-escapes in
// the key string; numbers are plain integers.
bool json_find(const std::string& body, const char* name, size_t& val_pos) {
    std::string pat = std::string("\"") + name + "\"";
    size_t pos = 0;
    bool in_str = false;
    for (size_t i = 0; i < body.size(); i++) {
        char ch = body[i];
        if (in_str) {
            if (ch == '\\') i++;
            else if (ch == '"') in_str = false;
            continue;
        }
        if (ch == '"') {
            if (body.compare(i, pat.size(), pat) == 0) {
                pos = i + pat.size();
                while (pos < body.size() &&
                       (body[pos] == ' ' || body[pos] == '\t'))
                    pos++;
                if (pos < body.size() && body[pos] == ':') {
                    pos++;
                    while (pos < body.size() &&
                           (body[pos] == ' ' || body[pos] == '\t'))
                        pos++;
                    val_pos = pos;
                    return true;
                }
            }
            in_str = true;
        }
    }
    return false;
}

bool json_int(const std::string& body, const char* name, int64_t& out) {
    size_t pos;
    if (!json_find(body, name, pos)) return false;
    size_t end = pos;
    if (end < body.size() && (body[end] == '-' || body[end] == '+')) end++;
    while (end < body.size() && body[end] >= '0' && body[end] <= '9') end++;
    if (end == pos) return false;
    return parse_i64_ascii(body.substr(pos, end - pos), out);
}

bool json_string(const std::string& body, const char* name,
                 std::string& out) {
    size_t pos;
    if (!json_find(body, name, pos)) return false;
    if (pos >= body.size() || body[pos] != '"') return false;
    pos++;
    out.clear();
    while (pos < body.size() && body[pos] != '"') {
        char ch = body[pos];
        if (ch == '\\' && pos + 1 < body.size()) {
            char esc = body[pos + 1];
            switch (esc) {
                case 'n': out += '\n'; break;
                case 't': out += '\t'; break;
                case 'r': out += '\r'; break;
                case 'b': out += '\b'; break;
                case 'f': out += '\f'; break;
                case 'u': {
                    // \uXXXX → UTF-8 (BMP only; surrogate pairs are rare
                    // in rate-limit keys and fall back to replacement).
                    if (pos + 5 < body.size()) {
                        unsigned cp = 0;
                        bool ok = true;
                        for (int k = 2; k <= 5; k++) {
                            char h = body[pos + k];
                            cp <<= 4;
                            if (h >= '0' && h <= '9') cp |= h - '0';
                            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
                            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
                            else { ok = false; break; }
                        }
                        if (ok) {
                            if (cp < 0x80) out += static_cast<char>(cp);
                            else if (cp < 0x800) {
                                out += static_cast<char>(0xC0 | (cp >> 6));
                                out += static_cast<char>(0x80 | (cp & 0x3F));
                            } else {
                                out += static_cast<char>(0xE0 | (cp >> 12));
                                out += static_cast<char>(
                                    0x80 | ((cp >> 6) & 0x3F));
                                out += static_cast<char>(0x80 | (cp & 0x3F));
                            }
                            pos += 6;
                            continue;
                        }
                    }
                    out += '?';
                    break;
                }
                default: out += esc; break;
            }
            pos += 2;
            continue;
        }
        out += ch;
        pos++;
    }
    return pos < body.size();
}

struct WireServer {
    int listen_fd = -1;
    int epoll_fd = -1;
    int wake_fd = -1;   // responder → IO thread
    uint16_t port = 0;
    int protocol = 0;   // 0 = RESP, 1 = HTTP/JSON
    std::thread io_thread;
    std::atomic<bool> running{false};

    std::unordered_map<int, Conn> conns;
    uint64_t next_gen = 1;

    // IO thread → driver.  Bounded like the reference's mpsc channel
    // (config.rs:311, default 100k): above the cap the IO thread stops
    // reading sockets (real backpressure), resuming once the driver
    // drains below half.
    std::mutex q_mu;
    std::condition_variable q_cv;
    std::deque<PendingRequest> queue;
    size_t queue_cap = 100000;
    bool paused = false;

    // Response routing: metas FIFO-paired with queue pops (see Inflight).
    std::deque<Inflight> inflight;  // guarded by q_mu

    // driver → IO thread (serialized responses per conn slot).
    std::mutex r_mu;
    std::deque<Response> responses;

    // /metrics snapshot pushed by the driver (HTTP protocol only).
    std::mutex m_mu;
    std::string metrics_text;
    // /health body pushed by the driver (failure-domain state machine:
    // "OK" | "retrying" | "degraded" | "recovering").
    std::string health_text = "OK";
    // /stats JSON snapshot pushed by the driver (insight tier, L3.75);
    // the disabled shape until the first push.
    std::string stats_text = "{\"insight\": {\"enabled\": false}}";

    // stats
    std::atomic<uint64_t> n_conns{0}, n_requests{0}, n_inline{0};

    bool start(const char* host, uint16_t want_port, int proto) {
        protocol = proto;
        listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
        if (listen_fd < 0) return false;
        int one = 1;
        setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_port = htons(want_port);
        if (inet_pton(AF_INET, host, &addr.sin_addr) != 1)
            addr.sin_addr.s_addr = INADDR_ANY;
        if (bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
                 sizeof(addr)) != 0)
            return false;
        if (listen(listen_fd, 1024) != 0) return false;
        socklen_t alen = sizeof(addr);
        getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
        port = ntohs(addr.sin_port);

        epoll_fd = epoll_create1(0);
        wake_fd = eventfd(0, EFD_NONBLOCK);
        epoll_event ev{};
        ev.events = EPOLLIN;
        ev.data.fd = listen_fd;
        epoll_ctl(epoll_fd, EPOLL_CTL_ADD, listen_fd, &ev);
        ev.events = EPOLLIN;
        ev.data.fd = wake_fd;
        epoll_ctl(epoll_fd, EPOLL_CTL_ADD, wake_fd, &ev);

        running = true;
        io_thread = std::thread([this] { loop(); });
        return true;
    }

    void stop() {
        if (!running.exchange(false)) return;
        uint64_t one = 1;
        ssize_t r = write(wake_fd, &one, sizeof(one));
        (void)r;
        q_cv.notify_all();
        if (io_thread.joinable()) io_thread.join();
        for (auto& [fd, c] : conns) close(fd);
        conns.clear();
        if (listen_fd >= 0) close(listen_fd);
        if (epoll_fd >= 0) close(epoll_fd);
        if (wake_fd >= 0) close(wake_fd);
    }

    // ---------------------------------------------------------- IO loop #

    void loop() {
        std::vector<epoll_event> events(256);
        int64_t last_idle_check = now_ms();
        while (running) {
            int n = epoll_wait(epoll_fd, events.data(),
                               static_cast<int>(events.size()), 1000);
            if (!running) break;
            for (int i = 0; i < n; i++) {
                int fd = events[i].data.fd;
                if (fd == listen_fd) {
                    accept_new();
                } else if (fd == wake_fd) {
                    uint64_t tmp;
                    while (read(wake_fd, &tmp, sizeof(tmp)) > 0) {
                    }
                    drain_responses();
                } else {
                    if (events[i].events & (EPOLLHUP | EPOLLERR)) {
                        drop_conn(fd);
                        continue;
                    }
                    if (events[i].events & EPOLLIN) handle_read(fd);
                    if (events[i].events & EPOLLOUT) handle_write(fd);
                }
            }
            int64_t t = now_ms();
            if (t - last_idle_check > 10000) {
                last_idle_check = t;
                std::vector<int> idle;
                for (auto& [fd, c] : conns)
                    if (t - c.last_activity_ms > IDLE_TIMEOUT_MS)
                        idle.push_back(fd);
                for (int fd : idle) drop_conn(fd);
            }
        }
    }

    void accept_new() {
        for (;;) {
            int fd = accept4(listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
            if (fd < 0) break;
            int one = 1;
            setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            Conn c;
            c.fd = fd;
            c.gen = next_gen++;
            c.last_activity_ms = now_ms();
            conns.emplace(fd, std::move(c));
            epoll_event ev{};
            // During a backpressure pause new connections must not arm
            // EPOLLIN, or level-triggered epoll spins on their bytes.
            ev.events = paused ? 0u : EPOLLIN;
            ev.data.fd = fd;
            epoll_ctl(epoll_fd, EPOLL_CTL_ADD, fd, &ev);
            n_conns++;
        }
    }

    void drop_conn(int fd) {
        auto it = conns.find(fd);
        if (it == conns.end()) return;
        epoll_ctl(epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
        close(fd);
        conns.erase(it);
    }

    // Recompute this connection's epoll interest from its state: read
    // unless globally paused / per-conn output-paused / half-closed.
    void rearm(Conn& c) {
        epoll_event ev{};
        const bool want_read = !paused && !c.out_paused && !c.rd_closed;
        ev.events = (want_read ? EPOLLIN : 0u) |
                    (c.want_write ? EPOLLOUT : 0u);
        ev.data.fd = c.fd;
        epoll_ctl(epoll_fd, EPOLL_CTL_MOD, c.fd, &ev);
    }

    void set_reading(bool enable) {
        paused = !enable;
        for (auto& [fd, c] : conns) rearm(c);
    }

    // Client half-closed its write side: no more input will arrive, but
    // pending responses (pipelined THROTTLEs, a deferred QUIT +OK) must
    // still be delivered before the connection drops — the asyncio
    // backends answer everything parsed before seeing EOF, and so must we.
    void half_close(int fd) {
        auto it = conns.find(fd);
        if (it == conns.end()) return;
        Conn& c = it->second;
        if (c.slots.empty() && c.wbuf.empty()) {
            drop_conn(fd);
            return;
        }
        c.rd_closed = true;
        rearm(c);
    }

    bool over_cap() {
        std::lock_guard<std::mutex> lk(q_mu);
        return queue.size() >= queue_cap;
    }

    void handle_read(int fd) {
        if (paused) return;
        auto it = conns.find(fd);
        if (it == conns.end()) return;
        Conn& c = it->second;
        if (c.rd_closed) return;
        if (c.draining || c.closing) {
            // A close-after slot is queued (QUIT, protocol error): no more
            // parsing, but keep consuming and discarding socket bytes —
            // leaving them unread makes level-triggered epoll spin hot.
            char junk[16384];
            for (;;) {
                ssize_t r = read(fd, junk, sizeof(junk));
                if (r > 0) continue;
                if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
                    return;
                if (r == 0) half_close(fd);  // deliver pending, then drop
                else drop_conn(fd);
                return;
            }
        }
        char tmp[16384];
        for (;;) {
            ssize_t r = read(fd, tmp, sizeof(tmp));
            if (r > 0) {
                c.rbuf.append(tmp, r);
                // Parse eagerly so a pipelining client never accumulates;
                // the 64 KB cap applies to the unparseable residue (one
                // oversized frame), matching the reference's incremental
                // read-then-parse loop (redis/mod.rs:97-127).
                process_buffer(c);
                auto again = conns.find(fd);
                if (again == conns.end() || &again->second != &c)
                    return;  // dropped (or rehashed after an erase)
                if (c.closing || c.draining || c.out_paused) return;
                if (c.rbuf.size() > MAX_CONN_BUFFER) {
                    emit_inline(c, "-ERR request too large\r\n", true);
                    return;
                }
                if (over_cap()) {
                    set_reading(false);
                    return;
                }
            } else if (r == 0) {
                half_close(fd);  // deliver pending responses, then drop
                return;
            } else {
                if (errno == EAGAIN || errno == EWOULDBLOCK) break;
                drop_conn(fd);
                return;
            }
        }
        c.last_activity_ms = now_ms();
    }

    void process_buffer(Conn& first) {
        // dispatch/emit_inline may drop the connection (write error),
        // destroying the Conn — re-resolve by fd + generation after every
        // step instead of holding a reference across them.
        const int fd = first.fd;
        const uint64_t gen = first.gen;
        bool enqueued = false;
        for (;;) {
            auto it = conns.find(fd);
            if (it == conns.end() || it->second.gen != gen) break;
            Conn& c = it->second;
            if (c.rbuf.empty() || c.closing || c.draining) break;
            if (protocol == 1) {
                int r = step_http(c);
                if (r == 0) break;
                enqueued |= r > 1;
                continue;
            }
            size_t consumed = 0;
            std::vector<std::string> args;
            std::vector<uint8_t> nulls;
            std::string err;
            int r = parse_command(c.rbuf, consumed, args, nulls, err);
            if (r == 0) break;
            if (r < 0) {
                emit_inline(c, "-" + err + "\r\n", true);
                break;
            }
            c.rbuf.erase(0, consumed);
            enqueued |= dispatch(c, args, nulls);
        }
        if (enqueued) q_cv.notify_one();
    }

    // ---------------------------------------------------- response order #

    // Move the contiguous ready prefix of the slot queue into the write
    // buffer, then flush.  A flushed close-after slot (QUIT, protocol
    // error, HTTP Connection: close) marks the connection closing and
    // discards anything queued behind it.  May drop the connection —
    // callers must re-resolve the Conn by fd afterwards.
    void pump_slots(Conn& c) {
        while (!c.slots.empty() && c.slots.front().ready) {
            Slot& s = c.slots.front();
            c.wbuf += s.payload;
            c.slots_bytes -= s.payload.size();
            const bool close_after = s.close_after;
            c.slots.pop_front();
            c.slot_base++;
            if (close_after) {
                c.closing = true;
                c.slots.clear();
                c.slots_bytes = 0;
                break;
            }
        }
        if (c.out_paused && c.slots.size() < OUT_SLOT_CAP / 2 &&
            c.slots_bytes < OUT_BYTES_CAP / 2) {
            c.out_paused = false;
            rearm(c);
        }
        // Half-closed client with every response delivered: close once
        // the write buffer drains (flush drops closing conns).
        if (c.rd_closed && c.slots.empty()) c.closing = true;
        flush(c);
    }

    void note_slot_pressure(Conn& c) {
        if (!c.out_paused && (c.slots.size() >= OUT_SLOT_CAP ||
                              c.slots_bytes >= OUT_BYTES_CAP)) {
            c.out_paused = true;
            rearm(c);
        }
    }

    // Append a ready (inline) response in arrival order.  Even though the
    // payload is known immediately, it must still wait behind any
    // unanswered THROTTLE slots ahead of it — pipelined responses leave
    // in exactly request order.
    void emit_inline(Conn& c, std::string payload, bool close_after) {
        Slot s;
        s.ready = true;
        s.close_after = close_after;
        s.payload = std::move(payload);
        c.slots_bytes += s.payload.size();
        c.slots.push_back(std::move(s));
        if (close_after) c.draining = true;
        note_slot_pressure(c);
        pump_slots(c);
    }

    // Reserve the next response slot for a driver-answered request and
    // return its sequence number.
    uint64_t reserve_slot(Conn& c) {
        const uint64_t seq = c.slot_base + c.slots.size();
        c.slots.emplace_back();
        note_slot_pressure(c);
        return seq;
    }

    // ------------------------------------------------------------ HTTP #

    static std::string http_payload(int status, const char* content_type,
                                    const std::string& body,
                                    bool keep_alive) {
        const char* reason =
            status == 200 ? "OK"
            : status == 400 ? "Bad Request"
            : status == 404 ? "Not Found"
            : "Internal Server Error";
        char head[256];
        int hn = snprintf(head, sizeof(head),
                          "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                          "Content-Length: %zu\r\nConnection: %s\r\n\r\n",
                          status, reason, content_type, body.size(),
                          keep_alive ? "keep-alive" : "close");
        return std::string(head, hn) + body;
    }

    void send_http(Conn& c, int status, const char* content_type,
                   const std::string& body, bool keep_alive) {
        emit_inline(c, http_payload(status, content_type, body, keep_alive),
                    !keep_alive);
    }

    // Returns 0 = need more data, 1 = handled inline, 2 = enqueued.
    int step_http(Conn& c) {
        size_t head_end = c.rbuf.find("\r\n\r\n");
        if (head_end == std::string::npos) return 0;
        std::string head = c.rbuf.substr(0, head_end);
        size_t line_end = head.find("\r\n");
        std::string request_line =
            head.substr(0, line_end == std::string::npos ? head.size()
                                                         : line_end);
        size_t sp1 = request_line.find(' ');
        size_t sp2 = request_line.find(' ', sp1 + 1);
        if (sp1 == std::string::npos || sp2 == std::string::npos) {
            send_http(c, 400, "text/plain", "bad request line", false);
            return 1;
        }
        std::string method = request_line.substr(0, sp1);
        std::string path = request_line.substr(sp1 + 1, sp2 - sp1 - 1);

        // Headers we care about: content-length, connection, deadline.
        int64_t content_length = 0;
        int64_t deadline_rel_ms = 0;
        bool keep_alive = true;
        size_t pos = line_end == std::string::npos ? head.size()
                                                   : line_end + 2;
        while (pos < head.size()) {
            size_t eol = head.find("\r\n", pos);
            if (eol == std::string::npos) eol = head.size();
            std::string line = head.substr(pos, eol - pos);
            pos = eol + 2;
            size_t colon = line.find(':');
            if (colon == std::string::npos) continue;
            std::string name = upper(line.substr(0, colon));
            std::string value = line.substr(colon + 1);
            while (!value.empty() && (value.front() == ' '))
                value.erase(0, 1);
            if (name == "CONTENT-LENGTH") {
                if (!parse_i64_ascii(value, content_length) ||
                    content_length < 0 ||
                    content_length >
                        static_cast<int64_t>(MAX_CONN_BUFFER)) {
                    send_http(c, 400, "text/plain", "bad content-length",
                              false);
                    return 1;
                }
            } else if (name == "CONNECTION") {
                keep_alive = upper(value) != "CLOSE";
            } else if (name == "X-THROTTLECRAB-DEADLINE-MS") {
                // Optional client deadline, relative ms; malformed or
                // non-positive values are ignored (deadline unset) —
                // a bad hint must not fail an otherwise-valid request.
                int64_t v;
                if (parse_i64_ascii(value, v) && v > 0)
                    deadline_rel_ms = v;
            }
        }
        size_t total = head_end + 4 + content_length;
        if (c.rbuf.size() < total) return 0;
        std::string body = c.rbuf.substr(head_end + 4, content_length);
        c.rbuf.erase(0, total);

        if (method == "GET" && path == "/health") {
            std::string text;
            {
                std::lock_guard<std::mutex> lk(m_mu);
                text = health_text;
            }
            send_http(c, 200, "text/plain", text, keep_alive);
            return 1;
        }
        if (method == "GET" && path == "/metrics") {
            std::string text;
            {
                std::lock_guard<std::mutex> lk(m_mu);
                text = metrics_text;
            }
            send_http(c, 200, "text/plain; version=0.0.4", text,
                      keep_alive);
            return 1;
        }
        if (method == "GET" && path == "/stats") {
            // Insight-tier analytics snapshot (L3.75), answered inline
            // like /health and /metrics — no Python round trip.
            std::string text;
            {
                std::lock_guard<std::mutex> lk(m_mu);
                text = stats_text;
            }
            send_http(c, 200, "application/json", text, keep_alive);
            return 1;
        }
        if (!(method == "POST" && path == "/throttle")) {
            send_http(c, 404, "text/plain", "Not Found", keep_alive);
            return 1;
        }

        PendingRequest req;
        req.conn_gen = c.gen;
        req.fd = c.fd;
        req.keep_alive = keep_alive;
        if (!json_string(body, "key", req.key)) {
            send_http(c, 400, "application/json",
                      "{\"error\": \"invalid request: missing key\"}",
                      keep_alive);
            return 1;
        }
        if (!json_int(body, "max_burst", req.max_burst) ||
            !json_int(body, "count_per_period", req.count_per_period) ||
            !json_int(body, "period", req.period)) {
            send_http(c, 400, "application/json",
                      "{\"error\": \"invalid request: missing field\"}",
                      keep_alive);
            return 1;
        }
        if (!json_int(body, "quantity", req.quantity))
            req.quantity = 1;  // http.rs:135
        if (deadline_rel_ms > 0)
            req.deadline_ms = now_ms() + deadline_rel_ms;
        req.slot_seq = reserve_slot(c);
        {
            std::lock_guard<std::mutex> lk(q_mu);
            queue.push_back(std::move(req));
        }
        n_requests++;
        return 2;
    }

    // Returns true if a THROTTLE landed in the pending queue.
    bool dispatch(Conn& c, std::vector<std::string>& args,
                  const std::vector<uint8_t>& nulls) {
        n_inline++;
        if (args.empty()) {
            emit_inline(c, "-ERR empty command\r\n", false);
            return false;
        }
        if (nulls[0]) {
            // Null bulk command name, like a non-bulk frame element.
            emit_inline(c, "-ERR invalid command format\r\n", false);
            return false;
        }
        const std::string cmd = upper(args[0]);
        if (cmd == "PING") {
            if (args.size() == 1) {
                emit_inline(c, "+PONG\r\n", false);
            } else if (args.size() == 2) {
                if (nulls[1]) {
                    // PING with a null message echoes null, matching the
                    // asyncio backend's echo of BulkString(None).
                    emit_inline(c, "$-1\r\n", false);
                } else {
                    char head[32];
                    int hn = snprintf(head, sizeof(head), "$%zu\r\n",
                                      args[1].size());
                    emit_inline(c,
                                std::string(head, hn) + args[1] + "\r\n",
                                false);
                }
            } else {
                emit_inline(
                    c,
                    "-ERR wrong number of arguments for 'ping' command\r\n",
                    false);
            }
            return false;
        }
        if (cmd == "QUIT") {
            emit_inline(c, "+OK\r\n", true);
            return false;
        }
        if (cmd != "THROTTLE") {
            emit_inline(c, "-ERR unknown command '" + cmd + "'\r\n", false);
            return false;
        }
        if (args.size() < 5 || args.size() > 7) {
            emit_inline(
                c,
                "-ERR wrong number of arguments for 'throttle' "
                "command\r\n",
                false);
            return false;
        }
        if (nulls[1]) {
            emit_inline(c, "-ERR invalid key\r\n", false);
            return false;
        }
        PendingRequest req;
        req.conn_gen = c.gen;
        req.fd = c.fd;
        req.key = args[1];
        // Null numeric args arrive as "" and fail the i64 parse, yielding
        // the same per-argument errors the asyncio backend produces.
        if (nulls[2] || !parse_i64_ascii(args[2], req.max_burst)) {
            emit_inline(c, "-ERR invalid max_burst\r\n", false);
            return false;
        }
        if (nulls[3] || !parse_i64_ascii(args[3], req.count_per_period)) {
            emit_inline(c, "-ERR invalid count_per_period\r\n", false);
            return false;
        }
        if (nulls[4] || !parse_i64_ascii(args[4], req.period)) {
            emit_inline(c, "-ERR invalid period\r\n", false);
            return false;
        }
        req.quantity = 1;
        if (args.size() >= 6 &&
            (nulls[5] || !parse_i64_ascii(args[5], req.quantity))) {
            emit_inline(c, "-ERR invalid quantity\r\n", false);
            return false;
        }
        // Optional 7th token: client deadline in relative milliseconds
        // (matches the asyncio backend's extended THROTTLE arity).
        if (args.size() == 7) {
            int64_t dl_ms;
            if (nulls[6] || !parse_i64_ascii(args[6], dl_ms)) {
                emit_inline(c, "-ERR invalid deadline_ms\r\n", false);
                return false;
            }
            if (dl_ms > 0) req.deadline_ms = now_ms() + dl_ms;
        }
        req.slot_seq = reserve_slot(c);
        {
            std::lock_guard<std::mutex> lk(q_mu);
            queue.push_back(std::move(req));
        }
        n_requests++;
        return true;
    }

    void flush(Conn& c) {
        while (!c.wbuf.empty()) {
            ssize_t w = write(c.fd, c.wbuf.data(), c.wbuf.size());
            if (w > 0) {
                c.wbuf.erase(0, w);
            } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
                break;
            } else {
                drop_conn(c.fd);
                return;
            }
        }
        bool want = !c.wbuf.empty();
        if (want != c.want_write) {
            c.want_write = want;
            rearm(c);
        }
        if (c.wbuf.empty() && c.closing) drop_conn(c.fd);
    }

    void handle_write(int fd) {
        auto it = conns.find(fd);
        if (it != conns.end()) flush(it->second);
    }

    void drain_responses() {
        if (paused) {
            std::unique_lock<std::mutex> lk(q_mu);
            if (queue.size() < queue_cap / 2) {
                lk.unlock();
                set_reading(true);
            }
        }
        std::deque<Response> local;
        {
            std::lock_guard<std::mutex> lk(r_mu);
            local.swap(responses);
        }
        // Fill every addressed slot first, then pump each connection once
        // — pipelined responses coalesce into fewer writes, and the ready
        // prefix leaves in exactly request order.
        std::vector<int> touched;
        for (auto& r : local) {
            auto it = conns.find(r.fd);
            if (it == conns.end() || it->second.gen != r.conn_gen)
                continue;  // connection died while the batch was in flight
            Conn& c = it->second;
            if (r.slot_seq < c.slot_base) continue;  // discarded by close
            const size_t idx = r.slot_seq - c.slot_base;
            if (idx >= c.slots.size()) continue;
            Slot& s = c.slots[idx];
            s.payload = std::move(r.payload);
            c.slots_bytes += s.payload.size();
            s.close_after = r.close_after;
            s.ready = true;
            if (touched.empty() || touched.back() != r.fd)
                touched.push_back(r.fd);
        }
        for (int fd : touched) {
            auto it = conns.find(fd);
            if (it != conns.end()) pump_slots(it->second);
        }
    }
};

}  // namespace

extern "C" {

void* ws_create() { return new WireServer(); }

// protocol: 0 = RESP, 1 = HTTP/JSON.
int ws_start(void* h, const char* host, uint16_t port, int protocol) {
    return static_cast<WireServer*>(h)->start(host, port, protocol) ? 0 : -1;
}

// Push a fresh Prometheus snapshot for GET /metrics (HTTP protocol).
void ws_set_metrics(void* h, const char* text, int64_t len) {
    auto* s = static_cast<WireServer*>(h);
    std::lock_guard<std::mutex> lk(s->m_mu);
    s->metrics_text.assign(text, len);
}

// Push the serving state for GET /health (HTTP protocol): "OK" while
// healthy, else the supervisor's state name (always HTTP 200 — a
// degraded node is still serving).
void ws_set_health(void* h, const char* text, int64_t len) {
    auto* s = static_cast<WireServer*>(h);
    std::lock_guard<std::mutex> lk(s->m_mu);
    s->health_text.assign(text, len);
}

// Push the insight tier's /stats JSON snapshot (HTTP protocol).
void ws_set_stats(void* h, const char* text, int64_t len) {
    auto* s = static_cast<WireServer*>(h);
    std::lock_guard<std::mutex> lk(s->m_mu);
    s->stats_text.assign(text, len);
}

uint16_t ws_port(void* h) { return static_cast<WireServer*>(h)->port; }

void ws_stop(void* h) { static_cast<WireServer*>(h)->stop(); }

void ws_destroy(void* h) {
    auto* s = static_cast<WireServer*>(h);
    s->stop();
    delete s;
}

// Blocks up to timeout_us for pending THROTTLE requests, then fills up to
// max_n of them.  Key bytes are concatenated into key_buf (cap key_buf_len)
// with offsets[n+1]; params land in the i64 arrays (stride 5: max_burst,
// count_per_period, period, quantity, remaining deadline budget in ns —
// 0 = no deadline, negative = already expired at pop); cookies (conn
// gen+fd) identify where the response goes.  Returns n (0 on
// timeout/shutdown).
int64_t ws_next_batch(void* h, int64_t timeout_us, int64_t max_n,
                      char* key_buf, int64_t key_buf_len, int64_t* offsets,
                      int64_t* params /* [5 * max_n] interleaved */,
                      uint64_t* cookie_gen, int32_t* cookie_fd) {
    auto* s = static_cast<WireServer*>(h);
    std::unique_lock<std::mutex> lk(s->q_mu);
    if (s->queue.empty()) {
        s->q_cv.wait_for(lk, std::chrono::microseconds(timeout_us), [&] {
            return !s->queue.empty() || !s->running;
        });
    }
    int64_t n = 0;
    int64_t key_off = 0;
    int64_t now = now_ms();
    offsets[0] = 0;
    while (n < max_n && !s->queue.empty()) {
        PendingRequest& req = s->queue.front();
        if (key_off + static_cast<int64_t>(req.key.size()) > key_buf_len) {
            // Progress guarantee: the first request always ships (the
            // caller sizes key_buf above the per-frame cap, so a single
            // key can never exceed it) — a full buffer only defers the
            // rest to the next call.
            if (n > 0) break;
            s->queue.pop_front();  // defensive: impossible oversized key
            continue;
        }
        memcpy(key_buf + key_off, req.key.data(), req.key.size());
        key_off += req.key.size();
        offsets[n + 1] = key_off;
        params[5 * n + 0] = req.max_burst;
        params[5 * n + 1] = req.count_per_period;
        params[5 * n + 2] = req.period;
        params[5 * n + 3] = req.quantity;
        // Remaining budget at pop time; clamp expired to -1 so the
        // driver can shed without re-reading the clock.
        params[5 * n + 4] =
            req.deadline_ms == 0
                ? 0
                : std::max<int64_t>((req.deadline_ms - now) * 1'000'000,
                                    -1);
        cookie_gen[n] = req.conn_gen;
        cookie_fd[n] = req.fd;
        s->inflight.push_back(
            {req.conn_gen, req.fd, req.slot_seq, req.keep_alive});
        s->queue.pop_front();
        n++;
    }
    return n;
}

// Complete n requests: results[5*i..] = (allowed, limit, remaining,
// reset_after, retry_after) as i64 (already whole seconds), status[i] != 0
// marks a validation failure mapped to the matching -ERR string.
void ws_respond(void* h, int64_t n, const uint64_t* cookie_gen,
                const int32_t* cookie_fd, const int64_t* results,
                const uint8_t* status) {
    auto* s = static_cast<WireServer*>(h);
    std::deque<Inflight> metas;
    {
        std::lock_guard<std::mutex> lk(s->q_mu);
        for (int64_t i = 0; i < n && !s->inflight.empty(); i++) {
            metas.push_back(s->inflight.front());
            s->inflight.pop_front();
        }
    }
    {
        std::lock_guard<std::mutex> lk(s->r_mu);
        for (int64_t i = 0; i < n; i++) {
            if (i >= static_cast<int64_t>(metas.size())) break;
            // The meta carries the response slot; without it (a driver
            // double-respond bug) the result cannot be ordered, so it is
            // dropped rather than mis-delivered.
            const Inflight& meta = metas[i];
            if (meta.conn_gen != cookie_gen[i] || meta.fd != cookie_fd[i])
                continue;  // driver responded out of order; unroutable
            std::string payload;
            if (s->protocol == 1) {
                std::string body;
                int code = 200;
                if (status[i] == 0) {
                    char buf[224];
                    int len = snprintf(
                        buf, sizeof(buf),
                        "{\"allowed\": %s, \"limit\": %lld, "
                        "\"remaining\": %lld, \"reset_after\": %lld, "
                        "\"retry_after\": %lld}",
                        results[5 * i + 0] ? "true" : "false",
                        static_cast<long long>(results[5 * i + 1]),
                        static_cast<long long>(results[5 * i + 2]),
                        static_cast<long long>(results[5 * i + 3]),
                        static_cast<long long>(results[5 * i + 4]));
                    body.assign(buf, len);
                } else if (status[i] == 4) {
                    // Shed by the front tier's admission control: 503,
                    // the HTTP overload status (clients must be able to
                    // tell "back off" from "server bug").
                    code = 503;
                    body = "{\"error\": \"server overloaded\"}";
                } else if (status[i] == 5) {
                    // Tenant slot quota: a capacity condition like
                    // overload, not a server bug — same 503 class.
                    code = 503;
                    body = "{\"error\": \"tenant capacity quota "
                           "exceeded\"}";
                } else if (status[i] == 6) {
                    // Client deadline lapsed before dispatch: 504 is
                    // the timeout status — distinct from overload so
                    // callers can size their deadlines, not back off.
                    code = 504;
                    body = "{\"error\": \"deadline exceeded\"}";
                } else {
                    code = 500;  // engine-level error (http.rs:148-157)
                    body = status[i] == 1
                               ? "{\"error\": \"quantity cannot be "
                                 "negative\"}"
                           : status[i] == 2
                               ? "{\"error\": \"invalid rate limit "
                                 "parameters\"}"
                               : "{\"error\": \"internal error\"}";
                }
                const char* reason = code == 200   ? "OK"
                                     : code == 503 ? "Service Unavailable"
                                     : code == 504 ? "Gateway Timeout"
                                                   : "Internal Server Error";
                char head[224];
                int hn = snprintf(
                    head, sizeof(head),
                    "HTTP/1.1 %d %s\r\nContent-Type: application/json\r\n"
                    "Content-Length: %zu\r\nConnection: %s\r\n\r\n",
                    code, reason, body.size(),
                    meta.keep_alive ? "keep-alive" : "close");
                payload.assign(head, hn);
                payload += body;
            } else if (status[i] == 0) {
                char buf[160];
                int len = snprintf(
                    buf, sizeof(buf),
                    "*5\r\n:%lld\r\n:%lld\r\n:%lld\r\n:%lld\r\n:%lld\r\n",
                    static_cast<long long>(results[5 * i + 0]),
                    static_cast<long long>(results[5 * i + 1]),
                    static_cast<long long>(results[5 * i + 2]),
                    static_cast<long long>(results[5 * i + 3]),
                    static_cast<long long>(results[5 * i + 4]));
                payload.assign(buf, len);
            } else if (status[i] == 1) {
                payload = "-ERR quantity cannot be negative\r\n";
            } else if (status[i] == 2) {
                payload = "-ERR invalid rate limit parameters\r\n";
            } else if (status[i] == 4) {
                payload = "-ERR server overloaded\r\n";
            } else if (status[i] == 5) {
                payload = "-ERR tenant capacity quota exceeded\r\n";
            } else if (status[i] == 6) {
                payload = "-ERR deadline exceeded\r\n";
            } else {
                payload = "-ERR internal error\r\n";
            }
            s->responses.push_back(
                {meta.conn_gen, meta.fd, meta.slot_seq,
                 s->protocol == 1 && !meta.keep_alive,
                 std::move(payload)});
        }
    }
    uint64_t one = 1;
    ssize_t r = write(s->wake_fd, &one, sizeof(one));
    (void)r;
}

// Requests parsed and queued but not yet popped by the driver — the
// wire-layer queue depth the front tier's admission control keys on.
int64_t ws_queue_depth(void* h) {
    auto* s = static_cast<WireServer*>(h);
    std::lock_guard<std::mutex> lk(s->q_mu);
    return static_cast<int64_t>(s->queue.size());
}

void ws_stats(void* h, uint64_t* out_conns, uint64_t* out_requests,
              uint64_t* out_commands) {
    auto* s = static_cast<WireServer*>(h);
    *out_conns = s->n_conns;
    *out_requests = s->n_requests;
    *out_commands = s->n_inline;
}

}  // extern "C"
