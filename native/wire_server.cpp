// Native RESP wire front-end for the TPU rate limiter.
//
// The reference's transport hot path is tokio Rust (redis/mod.rs); here the
// wire layer is a C++ epoll loop so the Python process spends its cycles
// only on the batched device decide.  Division of labor:
//
//   IO thread (C++):   accept, read, RESP parse, PING/QUIT/parse errors
//                      answered inline; THROTTLE requests assembled into
//                      a lock-protected pending queue (key bytes + i64
//                      params + connection cookie).
//   driver (Python):   ws_next_batch() blocks until requests are pending
//                      (or timeout), copies them into numpy arrays, runs
//                      TpuRateLimiter.rate_limit_batch, then ws_respond()
//                      hands the 5-integer results back.
//   IO thread (C++):   serializes RESP arrays into per-connection output
//                      buffers and flushes via epoll writability.
//
// The C++ side enforces the reference's connection hardening: 64 KB read
// buffer cap and 5-minute idle timeout (redis/mod.rs:83-149).  Command
// semantics mirror redis/mod.rs:150-296 (case-insensitive, argument
// validation order, exact error strings).
//
// C ABI only (ctypes); no Python.h dependency.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t MAX_CONN_BUFFER = 64 * 1024;     // redis/mod.rs:83
constexpr int64_t IDLE_TIMEOUT_MS = 300 * 1000;   // redis/mod.rs:99
constexpr int64_t MAX_BULK = 512LL * 1024 * 1024; // resp.rs:8
constexpr int64_t MAX_ARRAY = 1024 * 1024;        // resp.rs:9

int64_t now_ms() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

struct PendingRequest {
    uint64_t conn_gen;   // connection generation cookie
    int fd;
    std::string key;
    int64_t max_burst, count_per_period, period, quantity;
};

struct Conn {
    int fd = -1;
    uint64_t gen = 0;
    std::string rbuf;
    std::string wbuf;
    int64_t last_activity_ms = 0;
    bool closing = false;     // close once wbuf drains
    bool want_write = false;
};

// Incremental RESP array-of-bulk-strings parser (the only client frames the
// reference accepts for commands; inline commands are not supported there
// either).  Returns: 1 = one command parsed, 0 = need more data,
// -1 = protocol error (err filled).
int parse_command(const std::string& buf, size_t& consumed,
                  std::vector<std::string>& out, std::string& err) {
    out.clear();
    size_t pos = 0;
    auto read_line = [&](std::string& line) -> int {
        size_t idx = buf.find("\r\n", pos);
        if (idx == std::string::npos) return 0;
        line.assign(buf, pos, idx - pos);
        pos = idx + 2;
        return 1;
    };
    auto parse_int = [](const std::string& s, int64_t& v) -> bool {
        if (s.empty()) return false;
        size_t i = (s[0] == '-' || s[0] == '+') ? 1 : 0;
        if (i == s.size()) return false;
        for (size_t j = i; j < s.size(); j++)
            if (s[j] < '0' || s[j] > '9') return false;
        errno = 0;
        v = strtoll(s.c_str(), nullptr, 10);
        return errno == 0;
    };

    if (buf.empty()) return 0;
    if (buf[0] != '*') {
        err = "ERR expected array of commands";
        return -1;
    }
    std::string line;
    if (!read_line(line)) return 0;
    int64_t count;
    if (!parse_int(line.substr(1), count) || count < -1 ||
        count > MAX_ARRAY) {
        err = "ERR Invalid array size";
        return -1;
    }
    if (count <= 0) {
        consumed = pos;
        return 1;  // empty command → dispatch will answer
    }
    for (int64_t i = 0; i < count; i++) {
        if (pos >= buf.size()) return 0;
        if (buf[pos] != '$') {
            err = "ERR invalid command format";
            return -1;
        }
        if (!read_line(line)) return 0;
        int64_t len;
        if (!parse_int(line.substr(1), len) || len < -1 || len > MAX_BULK) {
            err = "ERR Invalid bulk string length";
            return -1;
        }
        if (len == -1) {
            out.emplace_back();  // null bulk → empty (invalid for args)
            continue;
        }
        if (buf.size() < pos + static_cast<size_t>(len) + 2) return 0;
        out.emplace_back(buf, pos, len);
        pos += len + 2;
    }
    consumed = pos;
    return 1;
}

bool parse_i64_ascii(const std::string& s, int64_t& v) {
    if (s.empty()) return false;
    size_t i = (s[0] == '-' || s[0] == '+') ? 1 : 0;
    if (i == s.size()) return false;
    for (size_t j = i; j < s.size(); j++)
        if (s[j] < '0' || s[j] > '9') return false;
    errno = 0;
    v = strtoll(s.c_str(), nullptr, 10);
    return errno != ERANGE;
}

std::string upper(const std::string& s) {
    std::string o = s;
    for (char& c : o)
        if (c >= 'a' && c <= 'z') c -= 32;
    return o;
}

struct WireServer {
    int listen_fd = -1;
    int epoll_fd = -1;
    int wake_fd = -1;   // responder → IO thread
    uint16_t port = 0;
    std::thread io_thread;
    std::atomic<bool> running{false};

    std::unordered_map<int, Conn> conns;
    uint64_t next_gen = 1;

    // IO thread → driver.  Bounded like the reference's mpsc channel
    // (config.rs:311, default 100k): above the cap the IO thread stops
    // reading sockets (real backpressure), resuming once the driver
    // drains below half.
    std::mutex q_mu;
    std::condition_variable q_cv;
    std::deque<PendingRequest> queue;
    size_t queue_cap = 100000;
    bool paused = false;

    // driver → IO thread (serialized responses per conn).
    std::mutex r_mu;
    std::deque<std::pair<std::pair<uint64_t, int>, std::string>> responses;

    // stats
    std::atomic<uint64_t> n_conns{0}, n_requests{0}, n_inline{0};

    bool start(const char* host, uint16_t want_port) {
        listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
        if (listen_fd < 0) return false;
        int one = 1;
        setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_port = htons(want_port);
        if (inet_pton(AF_INET, host, &addr.sin_addr) != 1)
            addr.sin_addr.s_addr = INADDR_ANY;
        if (bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
                 sizeof(addr)) != 0)
            return false;
        if (listen(listen_fd, 1024) != 0) return false;
        socklen_t alen = sizeof(addr);
        getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
        port = ntohs(addr.sin_port);

        epoll_fd = epoll_create1(0);
        wake_fd = eventfd(0, EFD_NONBLOCK);
        epoll_event ev{};
        ev.events = EPOLLIN;
        ev.data.fd = listen_fd;
        epoll_ctl(epoll_fd, EPOLL_CTL_ADD, listen_fd, &ev);
        ev.events = EPOLLIN;
        ev.data.fd = wake_fd;
        epoll_ctl(epoll_fd, EPOLL_CTL_ADD, wake_fd, &ev);

        running = true;
        io_thread = std::thread([this] { loop(); });
        return true;
    }

    void stop() {
        if (!running.exchange(false)) return;
        uint64_t one = 1;
        ssize_t r = write(wake_fd, &one, sizeof(one));
        (void)r;
        q_cv.notify_all();
        if (io_thread.joinable()) io_thread.join();
        for (auto& [fd, c] : conns) close(fd);
        conns.clear();
        if (listen_fd >= 0) close(listen_fd);
        if (epoll_fd >= 0) close(epoll_fd);
        if (wake_fd >= 0) close(wake_fd);
    }

    // ---------------------------------------------------------- IO loop #

    void loop() {
        std::vector<epoll_event> events(256);
        int64_t last_idle_check = now_ms();
        while (running) {
            int n = epoll_wait(epoll_fd, events.data(),
                               static_cast<int>(events.size()), 1000);
            if (!running) break;
            for (int i = 0; i < n; i++) {
                int fd = events[i].data.fd;
                if (fd == listen_fd) {
                    accept_new();
                } else if (fd == wake_fd) {
                    uint64_t tmp;
                    while (read(wake_fd, &tmp, sizeof(tmp)) > 0) {
                    }
                    drain_responses();
                } else {
                    if (events[i].events & (EPOLLHUP | EPOLLERR)) {
                        drop_conn(fd);
                        continue;
                    }
                    if (events[i].events & EPOLLIN) handle_read(fd);
                    if (events[i].events & EPOLLOUT) handle_write(fd);
                }
            }
            int64_t t = now_ms();
            if (t - last_idle_check > 10000) {
                last_idle_check = t;
                std::vector<int> idle;
                for (auto& [fd, c] : conns)
                    if (t - c.last_activity_ms > IDLE_TIMEOUT_MS)
                        idle.push_back(fd);
                for (int fd : idle) drop_conn(fd);
            }
        }
    }

    void accept_new() {
        for (;;) {
            int fd = accept4(listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
            if (fd < 0) break;
            int one = 1;
            setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            Conn c;
            c.fd = fd;
            c.gen = next_gen++;
            c.last_activity_ms = now_ms();
            conns.emplace(fd, std::move(c));
            epoll_event ev{};
            // During a backpressure pause new connections must not arm
            // EPOLLIN, or level-triggered epoll spins on their bytes.
            ev.events = paused ? 0u : EPOLLIN;
            ev.data.fd = fd;
            epoll_ctl(epoll_fd, EPOLL_CTL_ADD, fd, &ev);
            n_conns++;
        }
    }

    void drop_conn(int fd) {
        auto it = conns.find(fd);
        if (it == conns.end()) return;
        epoll_ctl(epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
        close(fd);
        conns.erase(it);
    }

    void set_reading(bool enable) {
        for (auto& [fd, c] : conns) {
            epoll_event ev{};
            ev.events = (enable ? EPOLLIN : 0u) |
                        (c.want_write ? EPOLLOUT : 0u);
            ev.data.fd = fd;
            epoll_ctl(epoll_fd, EPOLL_CTL_MOD, fd, &ev);
        }
    }

    bool over_cap() {
        std::lock_guard<std::mutex> lk(q_mu);
        return queue.size() >= queue_cap;
    }

    void handle_read(int fd) {
        if (paused) return;
        auto it = conns.find(fd);
        if (it == conns.end()) return;
        Conn& c = it->second;
        char tmp[16384];
        for (;;) {
            ssize_t r = read(fd, tmp, sizeof(tmp));
            if (r > 0) {
                c.rbuf.append(tmp, r);
                // Parse eagerly so a pipelining client never accumulates;
                // the 64 KB cap applies to the unparseable residue (one
                // oversized frame), matching the reference's incremental
                // read-then-parse loop (redis/mod.rs:97-127).
                process_buffer(c);
                auto again = conns.find(fd);
                if (again == conns.end() || &again->second != &c)
                    return;  // dropped (or rehashed after an erase)
                if (c.closing) return;
                if (c.rbuf.size() > MAX_CONN_BUFFER) {
                    send_raw(c, "-ERR request too large\r\n", true);
                    return;
                }
                if (over_cap()) {
                    paused = true;
                    set_reading(false);
                    return;
                }
            } else if (r == 0) {
                drop_conn(fd);
                return;
            } else {
                if (errno == EAGAIN || errno == EWOULDBLOCK) break;
                drop_conn(fd);
                return;
            }
        }
        c.last_activity_ms = now_ms();
    }

    void process_buffer(Conn& first) {
        // dispatch/send_raw may drop the connection (QUIT, write error),
        // destroying the Conn — re-resolve by fd + generation after every
        // step instead of holding a reference across them.
        const int fd = first.fd;
        const uint64_t gen = first.gen;
        bool enqueued = false;
        for (;;) {
            auto it = conns.find(fd);
            if (it == conns.end() || it->second.gen != gen) break;
            Conn& c = it->second;
            if (c.rbuf.empty() || c.closing) break;
            size_t consumed = 0;
            std::vector<std::string> args;
            std::string err;
            int r = parse_command(c.rbuf, consumed, args, err);
            if (r == 0) break;
            if (r < 0) {
                send_raw(c, "-" + err + "\r\n", true);
                break;
            }
            c.rbuf.erase(0, consumed);
            enqueued |= dispatch(c, args);
        }
        if (enqueued) q_cv.notify_one();
    }

    // Returns true if a THROTTLE landed in the pending queue.
    bool dispatch(Conn& c, std::vector<std::string>& args) {
        n_inline++;
        if (args.empty()) {
            send_raw(c, "-ERR empty command\r\n", false);
            return false;
        }
        const std::string cmd = upper(args[0]);
        if (cmd == "PING") {
            if (args.size() == 1) {
                send_raw(c, "+PONG\r\n", false);
            } else if (args.size() == 2) {
                char head[32];
                int hn = snprintf(head, sizeof(head), "$%zu\r\n",
                                  args[1].size());
                send_raw(c, std::string(head, hn) + args[1] + "\r\n",
                         false);
            } else {
                send_raw(
                    c,
                    "-ERR wrong number of arguments for 'ping' command\r\n",
                    false);
            }
            return false;
        }
        if (cmd == "QUIT") {
            send_raw(c, "+OK\r\n", true);
            return false;
        }
        if (cmd != "THROTTLE") {
            send_raw(c, "-ERR unknown command '" + cmd + "'\r\n", false);
            return false;
        }
        if (args.size() < 5 || args.size() > 6) {
            send_raw(
                c,
                "-ERR wrong number of arguments for 'throttle' "
                "command\r\n",
                false);
            return false;
        }
        PendingRequest req;
        req.conn_gen = c.gen;
        req.fd = c.fd;
        req.key = args[1];
        if (!parse_i64_ascii(args[2], req.max_burst)) {
            send_raw(c, "-ERR invalid max_burst\r\n", false);
            return false;
        }
        if (!parse_i64_ascii(args[3], req.count_per_period)) {
            send_raw(c, "-ERR invalid count_per_period\r\n", false);
            return false;
        }
        if (!parse_i64_ascii(args[4], req.period)) {
            send_raw(c, "-ERR invalid period\r\n", false);
            return false;
        }
        req.quantity = 1;
        if (args.size() == 6 &&
            !parse_i64_ascii(args[5], req.quantity)) {
            send_raw(c, "-ERR invalid quantity\r\n", false);
            return false;
        }
        {
            std::lock_guard<std::mutex> lk(q_mu);
            queue.push_back(std::move(req));
        }
        n_requests++;
        return true;
    }

    void send_raw(Conn& c, const std::string& data, bool then_close) {
        c.wbuf += data;
        if (then_close) c.closing = true;
        flush(c);
    }

    void flush(Conn& c) {
        while (!c.wbuf.empty()) {
            ssize_t w = write(c.fd, c.wbuf.data(), c.wbuf.size());
            if (w > 0) {
                c.wbuf.erase(0, w);
            } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
                break;
            } else {
                drop_conn(c.fd);
                return;
            }
        }
        bool want = !c.wbuf.empty();
        if (want != c.want_write) {
            c.want_write = want;
            epoll_event ev{};
            ev.events = EPOLLIN | (want ? EPOLLOUT : 0);
            ev.data.fd = c.fd;
            epoll_ctl(epoll_fd, EPOLL_CTL_MOD, c.fd, &ev);
        }
        if (c.wbuf.empty() && c.closing) drop_conn(c.fd);
    }

    void handle_write(int fd) {
        auto it = conns.find(fd);
        if (it != conns.end()) flush(it->second);
    }

    void drain_responses() {
        if (paused) {
            std::unique_lock<std::mutex> lk(q_mu);
            if (queue.size() < queue_cap / 2) {
                lk.unlock();
                paused = false;
                set_reading(true);
            }
        }
        std::deque<std::pair<std::pair<uint64_t, int>, std::string>> local;
        {
            std::lock_guard<std::mutex> lk(r_mu);
            local.swap(responses);
        }
        for (auto& [who, payload] : local) {
            auto it = conns.find(who.second);
            if (it == conns.end() || it->second.gen != who.first)
                continue;  // connection died while the batch was in flight
            it->second.wbuf += payload;
            flush(it->second);
        }
    }
};

}  // namespace

extern "C" {

void* ws_create() { return new WireServer(); }

int ws_start(void* h, const char* host, uint16_t port) {
    return static_cast<WireServer*>(h)->start(host, port) ? 0 : -1;
}

uint16_t ws_port(void* h) { return static_cast<WireServer*>(h)->port; }

void ws_stop(void* h) { static_cast<WireServer*>(h)->stop(); }

void ws_destroy(void* h) {
    auto* s = static_cast<WireServer*>(h);
    s->stop();
    delete s;
}

// Blocks up to timeout_us for pending THROTTLE requests, then fills up to
// max_n of them.  Key bytes are concatenated into key_buf (cap key_buf_len)
// with offsets[n+1]; params land in the i64 arrays; cookies (conn gen+fd)
// identify where the response goes.  Returns n (0 on timeout/shutdown).
int64_t ws_next_batch(void* h, int64_t timeout_us, int64_t max_n,
                      char* key_buf, int64_t key_buf_len, int64_t* offsets,
                      int64_t* params /* [4 * max_n] interleaved */,
                      uint64_t* cookie_gen, int32_t* cookie_fd) {
    auto* s = static_cast<WireServer*>(h);
    std::unique_lock<std::mutex> lk(s->q_mu);
    if (s->queue.empty()) {
        s->q_cv.wait_for(lk, std::chrono::microseconds(timeout_us), [&] {
            return !s->queue.empty() || !s->running;
        });
    }
    int64_t n = 0;
    int64_t key_off = 0;
    offsets[0] = 0;
    while (n < max_n && !s->queue.empty()) {
        PendingRequest& req = s->queue.front();
        if (key_off + static_cast<int64_t>(req.key.size()) > key_buf_len) {
            // Progress guarantee: the first request always ships (the
            // caller sizes key_buf above the per-frame cap, so a single
            // key can never exceed it) — a full buffer only defers the
            // rest to the next call.
            if (n > 0) break;
            s->queue.pop_front();  // defensive: impossible oversized key
            continue;
        }
        memcpy(key_buf + key_off, req.key.data(), req.key.size());
        key_off += req.key.size();
        offsets[n + 1] = key_off;
        params[4 * n + 0] = req.max_burst;
        params[4 * n + 1] = req.count_per_period;
        params[4 * n + 2] = req.period;
        params[4 * n + 3] = req.quantity;
        cookie_gen[n] = req.conn_gen;
        cookie_fd[n] = req.fd;
        s->queue.pop_front();
        n++;
    }
    return n;
}

// Complete n requests: results[5*i..] = (allowed, limit, remaining,
// reset_after, retry_after) as i64 (already whole seconds), status[i] != 0
// marks a validation failure mapped to the matching -ERR string.
void ws_respond(void* h, int64_t n, const uint64_t* cookie_gen,
                const int32_t* cookie_fd, const int64_t* results,
                const uint8_t* status) {
    auto* s = static_cast<WireServer*>(h);
    {
        std::lock_guard<std::mutex> lk(s->r_mu);
        for (int64_t i = 0; i < n; i++) {
            std::string payload;
            if (status[i] == 0) {
                char buf[160];
                int len = snprintf(
                    buf, sizeof(buf),
                    "*5\r\n:%lld\r\n:%lld\r\n:%lld\r\n:%lld\r\n:%lld\r\n",
                    static_cast<long long>(results[5 * i + 0]),
                    static_cast<long long>(results[5 * i + 1]),
                    static_cast<long long>(results[5 * i + 2]),
                    static_cast<long long>(results[5 * i + 3]),
                    static_cast<long long>(results[5 * i + 4]));
                payload.assign(buf, len);
            } else if (status[i] == 1) {
                payload = "-ERR quantity cannot be negative\r\n";
            } else if (status[i] == 2) {
                payload = "-ERR invalid rate limit parameters\r\n";
            } else {
                payload = "-ERR internal error\r\n";
            }
            s->responses.emplace_back(
                std::make_pair(cookie_gen[i], cookie_fd[i]),
                std::move(payload));
        }
    }
    uint64_t one = 1;
    ssize_t r = write(s->wake_fd, &one, sizeof(one));
    (void)r;
}

void ws_stats(void* h, uint64_t* out_conns, uint64_t* out_requests,
              uint64_t* out_commands) {
    auto* s = static_cast<WireServer*>(h);
    *out_conns = s->n_conns;
    *out_requests = s->n_requests;
    *out_commands = s->n_inline;
}

}  // extern "C"
