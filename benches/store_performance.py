"""In-process engine micro-benchmarks.

The criterion-suite equivalent (`throttlecrab-server/benches/
store_performance.rs:7-366`): single hot key, hot/cold 80/20, uniform
random, sequential, zipfian, high-cardinality sweeps, and the three cleanup
policies compared — but measured against the batched device engine, since
that is this framework's hot path.  Prints one JSON line per scenario.

Usage:
  python benches/store_performance.py [--cpu] [--batch 4096] [--iters 64]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def bench_scenario(limiter, name, key_ids, batch, iters, params, now0):
    """Time `iters` batches drawn from key_ids; returns decisions/s."""
    n = len(key_ids)
    burst, count, period = params
    keys = [f"bench:{i}" for i in range(int(key_ids.max()) + 1)]
    # warmup / compile (wire=True: the serving fast path — compact i32
    # outputs + certified kernel — is what every transport runs).
    limiter.rate_limit_batch(
        [keys[i] for i in key_ids[:batch]], burst, count, period, 1, now0,
        wire=True,
    )
    t0 = time.perf_counter()
    for it in range(iters):
        sel = key_ids[(it * batch) % n : (it * batch) % n + batch]
        if len(sel) < batch:
            sel = np.concatenate([sel, key_ids[: batch - len(sel)]])
        limiter.rate_limit_batch(
            [keys[i] for i in sel], burst, count, period, 1,
            now0 + it * 1_000_000, wire=True,
        )
    dt = time.perf_counter() - t0
    rate = iters * batch / dt
    print(json.dumps({
        "scenario": name,
        "decisions_per_sec": round(rate),
        "batch": batch,
        "iters": iters,
    }))
    return rate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=64)
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import throttlecrab_tpu  # noqa: F401

    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    rng = np.random.default_rng(3)
    B, iters = args.batch, args.iters
    now0 = 1_753_000_000 * 1_000_000_000
    total = B * iters
    params = (100, 10_000, 60)

    # Key distributions (store_performance.rs groups).
    scenarios = {
        "single_hot_key": np.zeros(total, np.int64),
        "hot_keys_80_20": np.where(
            rng.random(total) < 0.8,
            rng.integers(0, 20, total),  # 80% of traffic on 20 keys
            rng.integers(20, 2000, total),
        ),
        "uniform_random_2k": rng.integers(0, 2000, total),
        "sequential_2k": np.arange(total, dtype=np.int64) % 2000,
        "zipfian_100k": None,  # built below
        "high_cardinality_100k": rng.permutation(
            np.arange(total, dtype=np.int64) % 100_000
        ),
    }
    ranks = np.arange(1, 100_001, dtype=np.float64)
    p = ranks**-1.1
    p /= p.sum()
    scenarios["zipfian_100k"] = rng.choice(100_000, size=total, p=p)

    for name, ids in scenarios.items():
        limiter = TpuRateLimiter(capacity=1 << 18, keymap="auto")
        bench_scenario(limiter, name, ids, B, iters, params, now0)

    # Fused Pallas decision-kernel row (THROTTLECRAB_PALLAS_FUSED=1,
    # tpu/pallas_fused.py): the zipfian workload with every window
    # decided by ONE fused launch instead of the composed-XLA chain.
    # Off-TPU the fused kernel runs in Pallas interpret mode — correct
    # but emulated — so the row reports skipped there, per the
    # interpret-exclusion convention in docs/benchmark-results.md.
    import os

    import jax

    if jax.default_backend() == "tpu":
        prev_env = os.environ.get("THROTTLECRAB_PALLAS_FUSED")
        os.environ["THROTTLECRAB_PALLAS_FUSED"] = "1"
        try:
            limiter = TpuRateLimiter(capacity=1 << 18, keymap="auto")
            bench_scenario(
                limiter, "zipfian_100k_pallas_fused",
                scenarios["zipfian_100k"], B, iters, params, now0,
            )
        finally:
            # Restore (not pop): an operator-exported =1 must keep
            # governing the remaining scenarios, or one JSON session
            # silently mixes fused and XLA rates.
            if prev_env is None:
                os.environ.pop("THROTTLECRAB_PALLAS_FUSED", None)
            else:
                os.environ["THROTTLECRAB_PALLAS_FUSED"] = prev_env
    else:
        print(json.dumps({
            "scenario": "zipfian_100k_pallas_fused",
            "skipped": "non-TPU backend: the fused kernel would run in "
                       "interpret mode, which measures the emulator — "
                       "excluded from measurement",
            "batch": B,
        }))

    # Workload-pattern rps sweep: the configured request-rate knob
    # (count_per_period = 100/1000/10000) cycled sequentially over 100
    # hot keys, like the reference's workload_patterns rps_* group
    # (store_performance.rs:263-291).
    rps_ids = np.arange(total, dtype=np.int64) % 100
    for rate in (100, 1000, 10_000):
        limiter = TpuRateLimiter(capacity=1 << 12, keymap="auto")
        bench_scenario(
            limiter, f"workload_rps_{rate}", rps_ids, B, iters,
            (100, rate, 60), now0,
        )

    # Cleanup-policy comparison on the zipfian workload
    # (store comparison group in the reference bench).
    from throttlecrab_tpu.server.engine import BatchingEngine  # noqa: F401
    from throttlecrab_tpu.tpu.cleanup import make_policy

    for policy_name in ("periodic", "probabilistic", "adaptive"):
        limiter = TpuRateLimiter(capacity=1 << 18, keymap="auto")
        policy = make_policy(policy_name)
        ids = scenarios["zipfian_100k"]
        keys = [f"bench:{i}" for i in range(100_000)]
        limiter.rate_limit_batch(
            [keys[i] for i in ids[:B]], *params, 1, now0
        )
        t0 = time.perf_counter()
        for it in range(iters):
            sel = ids[(it * B) % total : (it * B) % total + B]
            if len(sel) < B:
                sel = np.concatenate([sel, ids[: B - len(sel)]])
            now = now0 + it * 1_000_000
            limiter.rate_limit_batch(
                [keys[i] for i in sel], *params, 1, now, wire=True
            )
            policy.record_ops(B)
            if policy.should_clean(now, len(limiter), limiter.total_capacity):
                freed = limiter.sweep(now)
                policy.after_sweep(now, freed, len(limiter) + freed)
        dt = time.perf_counter() - t0
        print(json.dumps({
            "scenario": f"policy_{policy_name}_zipfian",
            "decisions_per_sec": round(iters * B / dt),
            "batch": B,
            "iters": iters,
        }))

    # Concurrent-contention group: 1/2/4/8 clients hammering the engine
    # (store_performance.rs:87-115 sweeps tokio threads the same way).
    # Measures coalescing: throughput plus requests-per-launch.
    bench_contention(B, max(iters * B // 8, 2000))
    return 0


def bench_contention(batch_size: int, total_requests: int) -> None:
    """N concurrent clients issue single requests through the batching
    engine; the engine coalesces them into device launches.  Reports
    decisions/s and the achieved requests-per-launch (coalescing
    efficiency) per client count."""
    import asyncio

    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.metrics import Metrics
    from throttlecrab_tpu.server.types import ThrottleRequest
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    for n_clients in (1, 2, 4, 8):
        limiter = TpuRateLimiter(capacity=1 << 16, keymap="auto")
        metrics = Metrics.builder().max_denied_keys(0).build()
        engine = BatchingEngine(
            limiter,
            batch_size=batch_size,
            max_linger_us=200,
            metrics=metrics,
        )
        per_client = total_requests // n_clients

        async def run() -> float:
            # Warm the compile outside the timed window.
            await engine.throttle(ThrottleRequest("warm", 10, 100, 60, 1))

            async def client(c: int) -> None:
                for i in range(per_client):
                    await engine.throttle(
                        ThrottleRequest(
                            f"c{c}:k{i % 512}", 1 << 30, 1 << 30, 3600, 1
                        )
                    )

            t0 = time.perf_counter()
            await asyncio.gather(
                *(client(c) for c in range(n_clients))
            )
            return time.perf_counter() - t0

        dt = asyncio.run(run())
        decided = per_client * n_clients
        launches = max(metrics.device_launches - 1, 1)  # minus warmup
        print(json.dumps({
            "scenario": f"contention_{n_clients}_clients",
            "decisions_per_sec": round(decided / dt),
            "requests_per_launch": round(
                (metrics.batched_requests - 1) / launches, 1
            ),
            "clients": n_clients,
            "requests": decided,
        }))


if __name__ == "__main__":
    sys.exit(main())
