"""The five BASELINE.json benchmark configs, one JSON line each.

SURVEY §7.2 step 6's obligation: the driver's north-star config list,
measured against this framework's engine.

  1. single key, burst 10, 100/60s, 10k sequential checks
  2. 10k unique keys, uniform, batch=256, shared (10,100,60) params
  3. 1M keys, Zipf-1.1, batch=4096, heterogeneous params
     (the headline — bench.py owns it; a scaled-down pass runs here)
  4. 1M keys + 20% expired, periodic sweep interleaved every 1k batches
  5. multi-tenant: 64 tenants x 100k keys, psum-reduced allowed/denied
     counters across an 8-device mesh

Config 5 needs 8 devices: on a v5e-8 it uses the real mesh; elsewhere it
runs on 8 virtual CPU devices (set before JAX initializes), which
validates the collective layout but not ICI bandwidth.

Usage:
  python benches/baseline_configs.py [--cpu] [--quick] [--config N]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

NS = 1_000_000_000
T0 = 1_753_000_000 * NS


def out(config, name, rate, extra=None):
    line = {
        "config": config,
        "scenario": name,
        "decisions_per_sec": round(rate),
    }
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)


def config1(quick):
    """Single key, burst 10, 100/60s, sequential scalar checks (the
    reference's CPU AdaptiveStore baseline shape)."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    lim = TpuRateLimiter(capacity=1024, keymap="auto")
    n = 1_000 if quick else 10_000
    lim.rate_limit("cfg1", 10, 100, 60, 1, T0)  # compile
    t0 = time.perf_counter()
    for i in range(n):
        lim.rate_limit("cfg1", 10, 100, 60, 1, T0 + i * 1_000_000)
    out("1", f"single key, {n} sequential scalar checks",
        n / (time.perf_counter() - t0))


def config2(quick):
    """10k unique keys, uniform, batch=256, shared params."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    lim = TpuRateLimiter(capacity=1 << 15, keymap="auto")
    n_keys, batch = 10_000, 256
    iters = 64 if quick else 512
    keys = [f"cfg2:{i}" for i in range(n_keys)]
    rng = np.random.default_rng(2)
    sel = rng.integers(0, n_keys, (iters + 1, batch))
    lim.rate_limit_batch([keys[i] for i in sel[0]], 10, 100, 60, 1, T0)
    t0 = time.perf_counter()
    for it in range(1, iters + 1):
        lim.rate_limit_batch(
            [keys[i] for i in sel[it]], 10, 100, 60, 1,
            T0 + it * 1_000_000,
        )
    out("2", f"10k keys uniform, batch={batch}",
        iters * batch / (time.perf_counter() - t0))


def config3(quick):
    """Headline shape, scaled down — `python bench.py` is the real run."""
    import subprocess

    cmd = [sys.executable, str(pathlib.Path(__file__).parent.parent / "bench.py"),
           "--quick"]
    if "--cpu" in sys.argv:
        cmd.append("--cpu")
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1200)
    line = json.loads(r.stdout.strip().splitlines()[-1])
    out("3", "headline (bench.py --quick)", line["value"],
        {"note": "full run: python bench.py"})


def config4(quick):
    """Keys with 20% short-TTL traffic, periodic sweep every 1k batches."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    n_keys = 20_000 if quick else 200_000
    batch = 4096
    n_batches = 200 if quick else 1000
    sweep_every = 100 if quick else 1000
    lim = TpuRateLimiter(capacity=1 << (16 if quick else 19), keymap="auto")
    keys = [f"cfg4:{i}" for i in range(n_keys)]
    rng = np.random.default_rng(4)
    # 20% of traffic hits keys whose period makes them expire within the
    # run (short TTL); sweeps reclaim them.
    short = rng.random(n_keys) < 0.2
    periods = np.where(short, 1, 3600).astype(np.int64)
    sel = rng.integers(0, n_keys, (n_batches + 1, batch))
    lim.rate_limit_batch(
        [keys[i] for i in sel[0]], 10, 100, periods[sel[0]], 1, T0
    )
    swept = 0
    t0 = time.perf_counter()
    for it in range(1, n_batches + 1):
        now = T0 + it * 50_000_000  # 50ms per batch of virtual time
        lim.rate_limit_batch(
            [keys[i] for i in sel[it]], 10, 100, periods[sel[it]], 1, now
        )
        if it % sweep_every == 0:
            swept += lim.sweep(now)
    out("4", "20% expiring keys, periodic sweep interleaved",
        n_batches * batch / (time.perf_counter() - t0),
        {"slots_swept": int(swept)})


def config5(quick):
    """64 tenants x 100k keys over an 8-device mesh; allowed/denied
    totals are the kernel's psum-reduced global counters."""
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    import jax

    n_dev = min(8, len(jax.devices()))
    tenants = 64
    keys_per_tenant = 1_000 if quick else 10_000
    batch = 4096
    iters = 32 if quick else 128
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=1 << (15 if quick else 18),
        mesh=make_mesh(n_dev), keymap="auto", auto_grow=False,
    )
    rng = np.random.default_rng(5)
    t_sel = rng.integers(0, tenants, (iters + 1, batch))
    k_sel = rng.integers(0, keys_per_tenant, (iters + 1, batch))
    def batch_keys(it):
        return [
            f"t{t_sel[it, j]}:k{k_sel[it, j]}" for j in range(batch)
        ]
    lim.rate_limit_batch(batch_keys(0), 5, 100, 60, 1, T0)
    t0 = time.perf_counter()
    for it in range(1, iters + 1):
        lim.rate_limit_batch(
            batch_keys(it), 5, 100, 60, 1, T0 + it * 1_000_000
        )
    dt = time.perf_counter() - t0
    out("5", f"64 tenants x {keys_per_tenant} keys, {n_dev}-device mesh",
        iters * batch / dt,
        {"psum_allowed": lim.total_allowed,
         "psum_denied": lim.total_denied,
         "devices": n_dev})


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--config", type=int, default=0,
                    help="run one config (1-5); default all")
    args = ap.parse_args()

    # Config 5 needs >= 8 devices; request virtual CPU devices before
    # JAX initializes when the host has fewer.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import throttlecrab_tpu  # noqa: F401

    configs = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}
    todo = [args.config] if args.config else [1, 2, 3, 4, 5]
    for c in todo:
        configs[c](args.quick)
    return 0


if __name__ == "__main__":
    sys.exit(main())
