"""Two-node cluster decision throughput (DCN path, real sockets).

Spawns one peer server process (cluster RPC + HTTP health), builds an
in-process ClusterLimiter as node 0 against it, and drives Zipf-skewed
batches through rate_limit_many — the same batch API the serving engine
uses — reporting decisions/s for:

  - local-only traffic (keys owned by node 0: cluster overhead is one
    ownership partition, no RPC), and
  - the natural 2-node mix (~half the keys forward to the peer over TCP
    per batch, pipelined by the owner-routing layer).

The gap between the two is the price of the DCN hop on this host (both
processes share one vCPU here, so the mix number is a conservative
floor — on real separate hosts the peer decides in parallel).

Prints one JSON line per scenario.  --quick shrinks the workload.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

CLUSTER_A = 19381
CLUSTER_B = 19382
HTTP_B = 19383
NODES = f"127.0.0.1:{CLUSTER_A},127.0.0.1:{CLUSTER_B}"


def spawn_peer():
    env = dict(os.environ)
    env["THROTTLECRAB_PLATFORM"] = "cpu"
    env["THROTTLECRAB_CLUSTER_TIMEOUT_MS"] = "60000"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "throttlecrab_tpu.server",
            "--http", "--http-port", str(HTTP_B),
            "--cluster-nodes", NODES, "--cluster-index", "1",
            "--store", "adaptive", "--log-level", "warn",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def wait_healthy(proc, port, deadline_s=120):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            out = proc.stdout.read()
            raise RuntimeError(f"peer exited rc={proc.returncode}: {out}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=1
            ) as r:
                if r.status == 200:
                    return
        except Exception:
            time.sleep(0.3)
    raise TimeoutError("peer did not become healthy")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--depth", type=int, default=8,
                    help="batches per rate_limit_many window")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from throttlecrab_tpu.parallel.cluster import ClusterLimiter, node_of_key
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    peer = spawn_peer()
    try:
        wait_healthy(peer, HTTP_B)

        local = TpuRateLimiter(capacity=1 << 18, keymap="auto")
        cl = ClusterLimiter(local, NODES.split(","), 0, io_timeout_s=60.0)

        n_keys = 20_000 if args.quick else 100_000
        keys_all = [b"ck:%d" % i for i in range(n_keys)]
        local_keys = [k for k in keys_all if node_of_key(k, 2) == 0]

        rng = np.random.default_rng(7)
        now0 = 1_753_000_000_000_000_000

        def run(name, universe, windows):
            # Zipf-skewed draws from the given key universe.
            ranks = np.arange(1, len(universe) + 1, dtype=np.float64)
            p = ranks ** -1.1
            p /= p.sum()
            # warm + timed
            decided = 0
            t_start = None
            for w in range(windows + 2):
                batches = []
                for j in range(args.depth):
                    draw = rng.choice(len(universe), args.batch, p=p)
                    bkeys = [universe[i] for i in draw]
                    batches.append(
                        (bkeys, 10, 1000, 60, 1,
                         now0 + (w * args.depth + j) * 1_000_000)
                    )
                res = cl.rate_limit_many(batches, wire=True)
                assert len(res) == args.depth
                if w == 1:
                    t_start = time.perf_counter()
                elif w > 1:
                    decided += args.depth * args.batch
            dt = time.perf_counter() - t_start
            print(json.dumps({
                "scenario": name,
                "decisions_per_sec": round(decided / dt),
                "batch": args.batch,
                "depth": args.depth,
                "windows": windows,
            }), flush=True)

        windows = 4 if args.quick else 12
        run("cluster_local_only", local_keys, windows)
        run("cluster_2node_mix", keys_all, windows)
        stats = cl.peer_stats()[NODES.split(",")[1]]
        print(json.dumps({
            "scenario": "peer_stats",
            "forwarded": int(stats["forwarded"]),
            "failed": int(stats["failed"]),
        }), flush=True)
        return 0
    finally:
        peer.terminate()
        try:
            peer.wait(timeout=15)
        except subprocess.TimeoutExpired:
            peer.kill()
            peer.wait()


if __name__ == "__main__":
    sys.exit(main())
