"""Elastic-cluster decision throughput: ring-vs-legacy A/B and the
join/kill/rejoin timeline (per-node AND aggregate numbers, the
enterprise multi-machine reporting shape of arXiv:2603.29113).

Topology: N in-process nodes, each a real `ClusterLimiter` +
`ClusterServer` RPC listener on its own event-loop thread — the DCN
forwarding path runs over real TCP sockets; only process isolation is
elided (all nodes share this host's vCPU anyway, so spawned processes
would measure the same contention with extra startup noise).

Scenarios:

- ``--ab`` (default on): 2-node A/B — the same Zipf-skewed mixed
  workload through (a) legacy crc32-modulo routing (``vnodes=0``, the
  kill switch), (b) the consistent-hash ring, and (c) ring + warm
  replication.  (a) vs (b) isolates the ring lookup cost (must be
  within session noise); (c) adds the replica pump.
- ``--elastic`` (default on): the 3-node lifecycle timeline — per-node
  and aggregate decisions/s measured in each phase: 2-node steady,
  node-2 join (first windows after OP_JOIN, migration riding along),
  3-node steady, node-2 kill (breaker + replica takeover riding
  along), and rejoin.

Prints one JSON line per measurement.  --quick shrinks the workload.
Numbers are only comparable within one session (1-vCPU host, see
docs/benchmark-results.md).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NS = 1_000_000_000
T0 = 1_761_000_000 * NS


def free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class BenchNode:
    """In-process cluster node with a live RPC listener."""

    def __init__(self, index, nodes, capacity, **kw):
        from throttlecrab_tpu.parallel.cluster import (
            ClusterLimiter,
            ClusterServer,
        )
        from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

        kw.setdefault("io_timeout_s", 60.0)
        self.index = index
        self.limiter = TpuRateLimiter(capacity=capacity, keymap="auto")
        self.limiter.rate_limit_batch(["__warm__"], 5, 100, 60, 1, T0 - NS)
        self.cl = ClusterLimiter(self.limiter, nodes, index, **kw)
        self.loop = asyncio.new_event_loop()
        self.srv = ClusterServer(
            "127.0.0.1", int(nodes[index].rpartition(":")[2]),
            self.cl.local, self.cl.device_lock, cluster=self.cl,
        )
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        asyncio.run_coroutine_threadsafe(
            self.srv.start(), self.loop
        ).result(timeout=10)

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def kill(self):
        if getattr(self, "_dead", False):
            return
        self._dead = True
        asyncio.run_coroutine_threadsafe(
            self.srv.stop(), self.loop
        ).result(timeout=10)
        self.cl.close()
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)


def zipf_batches(rng, universe, batch, depth, base_now, step):
    ranks = np.arange(1, len(universe) + 1, dtype=np.float64)
    p = ranks ** -1.1
    p /= p.sum()
    batches = []
    for j in range(depth):
        draw = rng.choice(len(universe), batch, p=p)
        batches.append(
            ([universe[i] for i in draw], 10, 1000, 60, 1,
             base_now + (step * depth + j) * 1_000_000)
        )
    return batches


def drive(node, rng, universe, batch, depth, windows, base_now,
          warm=1):
    """Windows through one frontend; returns (decisions/s, decisions)."""
    decided = 0
    t_start = time.perf_counter() if warm == 0 else None
    for w in range(windows + warm):
        res = node.cl.rate_limit_many(
            zipf_batches(rng, universe, batch, depth, base_now, w),
            wire=True,
        )
        assert len(res) == depth
        if warm and w == warm - 1:
            t_start = time.perf_counter()
        if w >= warm:
            decided += depth * batch
    dt = time.perf_counter() - t_start
    return decided / dt, decided


def emit(**row):
    print(json.dumps(row), flush=True)


def run_ab(args):
    """2-node mixed-workload A/B: legacy modulo vs ring vs ring+replica."""
    rng = np.random.default_rng(11)
    n_keys = 20_000 if args.quick else 60_000
    universe = [b"ab:%d" % i for i in range(n_keys)]
    windows = 3 if args.quick else 8
    for mode, kw in (
        ("legacy_modulo", dict(vnodes=0, replicate=False)),
        ("ring", dict(vnodes=128, replicate=False)),
        ("ring_replicate", dict(vnodes=128, replicate=True)),
    ):
        ports = free_ports(2)
        nodes = [f"127.0.0.1:{p}" for p in ports]
        cap = max(n_keys * 2, 1 << 16)
        a = BenchNode(0, nodes, cap, **kw)
        b = BenchNode(1, nodes, cap, **kw)
        try:
            if kw["vnodes"]:
                a.cl.announce_join_all()
                b.cl.announce_join_all()
            rate, decided = drive(
                a, rng, universe, args.batch, args.depth, windows, T0
            )
            emit(scenario=f"ab_2node_mix_{mode}",
                 decisions_per_sec=round(rate), batch=args.batch,
                 depth=args.depth, windows=windows)
        finally:
            a.kill()
            b.kill()


def run_elastic(args):
    """3-node lifecycle timeline with per-node + aggregate numbers."""
    rng = np.random.default_rng(13)
    n_keys = 20_000 if args.quick else 60_000
    universe = [b"el:%d" % i for i in range(n_keys)]
    windows = 2 if args.quick else 5
    ports = free_ports(3)
    node_addrs = [f"127.0.0.1:{p}" for p in ports]
    cap = max(n_keys * 2, 1 << 16)
    kw = dict(vnodes=128, replicate=True)
    live = {}
    now = [T0]

    def phase(name, indices):
        total_rate = 0.0
        for i in indices:
            rate, _ = drive(
                live[i], rng, universe, args.batch, args.depth, windows,
                now[0],
            )
            now[0] += windows * args.depth * 1_000_000 + NS
            emit(scenario=f"elastic_{name}", node=i,
                 decisions_per_sec=round(rate))
            total_rate += rate
        emit(scenario=f"elastic_{name}", node="aggregate",
             decisions_per_sec=round(total_rate), live_nodes=len(indices))

    try:
        live[0] = BenchNode(0, node_addrs, cap, **kw)
        live[1] = BenchNode(1, node_addrs, cap, **kw)
        live[0].cl.announce_join_all()
        live[1].cl.announce_join_all()
        phase("steady_2node", (0, 1))

        # JOIN: node 2 enters; the first windows ride the migration.
        t_join = time.perf_counter()
        live[2] = BenchNode(2, node_addrs, cap, **kw)
        live[2].cl.announce_join_all()
        phase("join", (0, 1, 2))
        emit(scenario="elastic_join_meta",
             join_wall_s=round(time.perf_counter() - t_join, 3),
             migrated_in=live[2].cl.migrated_in)
        phase("steady_3node", (0, 1, 2))

        # KILL: node 2 dies; survivors absorb (breaker + takeover ride
        # the first windows).
        live[2].kill()
        phase("kill", (0, 1))
        emit(scenario="elastic_kill_meta",
             takeovers=[live[i].cl.takeover_count for i in (0, 1)],
             replica_rows=[len(live[i].cl.replica_store) for i in (0, 1)])

        # REJOIN: node 2 returns and drains its (empty) table.
        live[2] = BenchNode(2, node_addrs, cap, **kw)
        live[2].cl.announce_join_all()
        phase("rejoin", (0, 1, 2))
    finally:
        for n in live.values():
            try:
                n.kill()
            except Exception:
                pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--depth", type=int, default=4,
                    help="batches per rate_limit_many window")
    ap.add_argument("--ab-only", action="store_true")
    ap.add_argument("--elastic-only", action="store_true")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    if not args.elastic_only:
        run_ab(args)
    if not args.ab_only:
        run_elastic(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
