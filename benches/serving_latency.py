"""Decision-latency percentiles at batch 4096 — the second clause of the
BASELINE north star (p99 decision latency < 1 ms at batch = 4096,
BASELINE.md:49-53).

Measures, per window of `--batch` requests against 1 M interned keys:

  engine path   — host prepare (C++ tk_prepare_batch when available) +
                  one device launch + result fetch, the exact path every
                  transport runs (dispatch_wire_window round trip).
  kernel only   — the device-resident by-id scan step alone (what a
                  PCIe-attached deployment pays once inputs are
                  resident): one launch + 8 B/request fetch.

Each window's wall time IS the decision latency of every request in it
(requests are answered together when the window's fetch completes), so
the per-window distribution is the per-request latency distribution.

Prints one JSON line per path with p50/p90/p99/max in ms plus the
implied decisions/s.  Run with --cpu off-TPU; on the real chip, run
through a healthy tunnel and mind the fixed ~65 ms relay RTT
(docs/tpu-launch-profile.md) — the tunnel number measures the lab link,
not the chip.

Usage: python benches/serving_latency.py [--cpu] [--batch 4096]
       [--windows 64] [--keys 1000000]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def percentiles(samples_ms):
    s = np.sort(np.asarray(samples_ms))
    return {
        "p50_ms": round(float(np.percentile(s, 50)), 3),
        "p90_ms": round(float(np.percentile(s, 90)), 3),
        "p99_ms": round(float(np.percentile(s, 99)), 3),
        "max_ms": round(float(s[-1]), 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--windows", type=int, default=64)
    ap.add_argument("--keys", type=int, default=1_000_000)
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import throttlecrab_tpu  # noqa: F401

    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    B, W, NK = args.batch, args.windows, args.keys
    now0 = 1_753_000_000 * 1_000_000_000
    rng = np.random.default_rng(5)

    lim = TpuRateLimiter(capacity=max(NK * 2, 1 << 16), keymap="auto")
    km = lim.keymap
    native = hasattr(km, "prepare_batch")
    print(
        f"keymap={'native' if native else 'python'} batch={B} "
        f"windows={W} keys={NK}",
        file=sys.stderr,
    )

    # Zipf-1.1 traffic over NK keys, params matching the headline bench.
    ranks = np.arange(1, NK + 1, dtype=np.float64)
    p = ranks**-1.1
    p /= p.sum()
    draws = rng.choice(NK, size=(W + 8) * B, p=p).astype(np.int64)

    keys = [b"lat:%d" % i for i in range(NK)]
    params = np.array([[100, 10_000, 60, 1]], np.int64).repeat(B, 0)

    def frame(ids):
        sel = [keys[i] for i in ids]
        blob = b"".join(sel)
        offs = np.cumsum([0] + [len(k) for k in sel]).astype(np.int64)
        return (blob, offs, params)

    # --- engine path: dispatch_wire_window round trips ------------------
    samples = []
    for w in range(W + 8):
        ids = draws[w * B : (w + 1) * B]
        now = now0 + w * 1_000_000
        t0 = time.perf_counter()
        if native:
            h = lim.dispatch_wire_window([frame(ids)], now)
            h.fetch()
        else:
            lim.rate_limit_batch(
                [keys[i] for i in ids], 100, 10_000, 60, 1, now, wire=True
            )
        dt = (time.perf_counter() - t0) * 1e3
        if w >= 8:  # first windows include compile
            samples.append(dt)
    stats = percentiles(samples)
    print(json.dumps({
        "path": "engine (prepare+launch+fetch)",
        "batch": B,
        **stats,
        "decisions_per_sec": round(B / (np.mean(samples) / 1e3)),
    }))

    # --- kernel-only: device-resident by-id scan ------------------------
    if native:
        # Fresh limiter so id i == key i (the engine run above interned
        # keys in traffic order).
        lim = TpuRateLimiter(capacity=max(NK * 2, 1 << 16), keymap="auto")
        km = lim.keymap
        km.intern(keys)  # host-only registration, untimed
        em = np.full(NK, 6_000_000, np.int64)
        tol = em * 100
        rows = lim.table.upload_id_rows(km.resolve_all(), em, tol)
        samples_k = []
        for w in range(W + 8):
            ids = draws[w * B : (w + 1) * B]
            now = np.array([now0 + w * 1_000_000], np.int64)
            t0 = time.perf_counter()
            out = lim.table.check_many_ids(
                rows, ids.astype(np.int32).reshape(1, B), now, 1,
                with_degen=False, compact="cur",
            )
            np.asarray(out)  # fetch = decision delivery
            dt = (time.perf_counter() - t0) * 1e3
            if w >= 8:
                samples_k.append(dt)
        stats_k = percentiles(samples_k)
        print(json.dumps({
            "path": "kernel (resident launch+fetch)",
            "batch": B,
            **stats_k,
            "decisions_per_sec": round(B / (np.mean(samples_k) / 1e3)),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
