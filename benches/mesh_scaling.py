"""Mesh scaling proof: config-5 shape across D = 1/2/4/8 devices.

ISSUE 6's acceptance bench: the BASELINE config-5 multi-tenant shape
(64 tenants over ~100k keys, batch 4096, psum-reduced counters) run at
every mesh width the backend offers, with the insight tier OFF and ON
at each width — all in ONE session (the benchmarking convention:
docs/benchmark-results.md; the 1-vCPU build host's delivered-CPU
varies ±2× between sessions, so only same-session A/Bs mean anything).

On real hardware the mesh widths are physical chips; elsewhere the
sweep runs on 8 virtual CPU devices, which validates the collective
layout and measures the end-to-end host+launch path, NOT ICI scaling —
the virtual devices share one core, so decisions/s staying FLAT with D
is the honest expectation there, while per-device work (capacity,
keymap load) drops ~linearly with D.

Also measured, same session: the vectorized host-side shard routing
(one numpy CRC32 pass, parallel/tenants.py) against the per-key
zlib.crc32 loop it replaced — the host-side satellite win.

Usage:
  python benches/mesh_scaling.py [--quick] [--keys-per-tenant N]
                                 [--batch N] [--iters N]

One JSON line per measurement, then a summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time
import zlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

NS = 1_000_000_000
T0 = 1_753_000_000 * NS
TENANTS = 64


def out(line: dict) -> None:
    print(json.dumps(line), flush=True)


def bench_routing(keys, n_shards: int) -> dict:
    """Vectorized CRC32 routing vs the per-key loop, same keys."""
    from throttlecrab_tpu.parallel.tenants import crc32_rows, key_matrix

    bkeys = [k.encode() for k in keys]

    def loop():
        return np.fromiter(
            (zlib.crc32(k) % n_shards for k in bkeys), np.int32,
            count=len(bkeys),
        )

    def vectorized():
        mat, lens = key_matrix(bkeys)
        return (crc32_rows(mat, lens) % np.uint32(n_shards)).astype(
            np.int32
        )

    assert (loop() == vectorized()).all(), "routing twins diverged"
    best = {}
    for name, fn in (("loop", loop), ("vectorized", vectorized)):
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        best[name] = min(times)
    n = len(bkeys)
    return {
        "metric": "host shard routing (per-key zlib loop vs one numpy "
                  "CRC32 pass)",
        "keys": n,
        "loop_us_per_key": round(best["loop"] / n * 1e6, 4),
        "vectorized_us_per_key": round(best["vectorized"] / n * 1e6, 4),
        "speedup": round(best["loop"] / best["vectorized"], 2),
    }


def bench_mesh(D, n_dev_avail, keys, tenants_on, insight, batch, iters,
               warm):
    """Decisions/s for one (mesh width, insight) point."""
    import jax

    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )
    from throttlecrab_tpu.parallel.tenants import TenantRegistry

    n_keys = len(keys)
    depth = 4  # engine-shaped: K windows per mesh launch, wire mode
    rng = np.random.default_rng(1000 + D)
    sel = rng.integers(0, n_keys, ((warm + iters) * depth, batch))
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=max(2 * n_keys // D, 4096),
        mesh=make_mesh(min(D, n_dev_avail)),
        keymap="auto",
        auto_grow=False,
        insight=insight,
        tenants=(
            TenantRegistry(max_tenants=TENANTS + 4) if tenants_on else None
        ),
    )
    now = [T0]

    def one_pass(n_launches):
        """The serving shape: K-deep scan windows through
        rate_limit_many in WIRE mode (the engine's backlog path and
        compact output ladder), not bare non-wire single batches."""
        t0 = time.perf_counter()
        for it in range(n_launches):
            windows = []
            for j in range(depth):
                now[0] += 1_000_000_000
                windows.append((
                    [keys[i] for i in sel[it * depth + j]],
                    5, 100, 60, 1, now[0],
                ))
            lim.rate_limit_many(windows, wire=True)
        return n_launches * depth * batch / (time.perf_counter() - t0)

    one_pass(warm + iters)  # compile + intern every touched key

    # Best of 2 timed passes on the warm limiter (the repo bench
    # idiom: 1-vCPU container scheduling swings single runs wildly).
    rate = max(one_pass(iters), one_pass(iters))
    poll_ms = 0.0
    if insight:
        t1 = time.perf_counter()
        lim.table.insight_counts()
        tk = lim.table.insight_topk(64)
        np.asarray(tk[0]), np.asarray(tk[1])
        poll_ms = (time.perf_counter() - t1) * 1e3
    return {
        "devices": D,
        "insight": insight,
        "decisions_per_sec": round(rate),
        "poll_ms": round(poll_ms, 3),
        "psum_allowed": lim.total_allowed,
        "psum_denied": lim.total_denied,
        "platform": jax.devices()[0].platform,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--keys-per-tenant", type=int, default=0,
                    help="keys per tenant (default: config-5 shape, "
                    "~100k keys total; --quick quarters it)")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=0,
                    help="timed batches per point (default 32; "
                    "--quick 8)")
    args = ap.parse_args()

    # The sweep needs up to 8 devices; request virtual CPU devices
    # before JAX initializes when the host has fewer.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    import throttlecrab_tpu  # noqa: F401  (enables x64)

    n_dev = len(jax.devices())
    per_tenant = args.keys_per_tenant or (400 if args.quick else 1562)
    iters = args.iters or (8 if args.quick else 32)
    warm = 2 if args.quick else 4
    keys = [
        f"t{t}:k{i}" for t in range(TENANTS) for i in range(per_tenant)
    ]
    out({
        "metric": "mesh_scaling setup",
        "tenants": TENANTS,
        "keys": len(keys),
        "batch": args.batch,
        "iters": iters,
        "devices_available": n_dev,
    })

    # Satellite: host-side routing win, same session.
    rng = np.random.default_rng(7)
    route_keys = [keys[i] for i in rng.integers(0, len(keys), 8 * 4096)]
    out(bench_routing(route_keys, max(n_dev, 2)))

    results = []
    for D in (1, 2, 4, 8):
        if D > n_dev:
            out({"metric": "mesh point skipped", "devices": D,
                 "reason": f"backend exposes {n_dev}"})
            continue
        for insight in (False, True):
            r = bench_mesh(
                D, n_dev, keys, tenants_on=True, insight=insight,
                batch=args.batch, iters=iters, warm=warm,
            )
            results.append(r)
            out(r)

    # Summary: per-width insight overhead + scaling vs D=1.
    by = {(r["devices"], r["insight"]): r["decisions_per_sec"]
          for r in results}
    summary = {"metric": "mesh_scaling summary (config-5 shape, "
                         "same-session A/B)"}
    base = by.get((1, False))
    for D in (1, 2, 4, 8):
        off, on = by.get((D, False)), by.get((D, True))
        if off is None or on is None:
            continue
        summary[f"d{D}_off"] = off
        summary[f"d{D}_on"] = on
        summary[f"d{D}_insight_overhead_frac"] = round(1 - on / off, 4)
        if base:
            summary[f"d{D}_vs_d1"] = round(off / base, 3)
    out(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
